"""Disaggregated KV handoff: layout descriptors + transfer protocol.

The reference moves KV from prefill GPU to decode GPU with NIXL RDMA
(ref: docs/design-docs/disagg-serving.md; dynamo.nixl_connect). On TPU there
are no RDMA verbs; the v1 data plane is a host-relay DCN transfer —

    prefill HBM --(fused gather, one D2H DMA)--> host --(request plane,
    chunked binary frames)--> decode host --(one H2D + fused scatter)--> HBM

with a serialized layout descriptor bridging the two pools exactly like the
reference's `SerializedNixlBlockLayout` (kvbm-design.md §Remote Memory
Integration). Intra-mesh ICI collective-permute handoff is the v2 fast path
(parallel/transfer planning); this module owns the wire protocol + the
prefill-side pending-transfer registry either path shares.

Flow (ref §3.4): PrefillRouter sends the prompt to a prefill worker with
max_tokens=1 + annotation `prefill_only`; the prefill engine parks the
sequence's pages in a PendingTransferTable and answers with
`kv_transfer_params` (transfer id + route + layout + first token). The
decode worker pulls the blocks over its `kv_pull` endpoint before admitting
the sequence, then decodes from position prompt_len.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Iterator, Optional

import numpy as np

from ..runtime import conformance

# Target bytes per kv_pull response frame (well under codec MAX_FRAME).
TRANSFER_CHUNK_BYTES = 4 << 20


@dataclasses.dataclass
class KvLayoutDescriptor:
    """Serialized block-layout metadata exchanged between pools."""

    n_layers: int
    kv_heads: int
    head_dim: int
    page_size: int
    dtype: str  # numpy dtype name of the wire payload
    kv_dims: int = 2  # 2 for separate K/V stacks, 1 for MLA latent cache
    # Quantized pools stamp their scheme so a packed-uint8 pool can never
    # silently pair with a bf16 pool (compatible() compares the whole
    # descriptor): disagg transfers of int8 pools are rejected at the
    # worker CLI today, but the descriptor must still tell them apart.
    kv_dtype: str = "model"
    scale_lanes: int = 0

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, data: dict) -> "KvLayoutDescriptor":
        return cls(**{f.name: data[f.name]
                      for f in dataclasses.fields(cls) if f.name in data})

    def page_bytes(self) -> int:
        return (self.n_layers * self.kv_dims * self.page_size * self.kv_heads
                * self.head_dim * np.dtype(self.dtype).itemsize)

    def compatible(self, other: "KvLayoutDescriptor") -> bool:
        return self == other


@dataclasses.dataclass
class PendingTransfer:
    transfer_id: str
    page_ids: list[int]  # physical pages in the prefill pool, page order
    release: Callable[[], None]  # returns the pages to the prefill pool
    layout: KvLayoutDescriptor
    prompt_len: int
    created_at: float = dataclasses.field(default_factory=time.monotonic)

    @property
    def streaming(self) -> bool:
        return False


class StreamingTransfer(PendingTransfer):
    """A transfer registered while its prompt is STILL PREFILLING
    (disagg chunked handoff, docs/disaggregation.md): the prefill
    scheduler appends page ids per completed chunk and finishes with the
    first sampled token; the pull side waits on the chunk condition and
    streams pages as they become ready — chunk i moves while chunk i+1
    computes.

    Thread model: append/finish/fail run on the prefill scheduler thread,
    wait_ready on a puller thread (asyncio.to_thread). One condition
    serializes them. `fail` claims the table entry itself so release runs
    exactly once whether or not a puller ever arrived."""

    def __init__(self, *args, table: "PendingTransferTable", **kwargs):
        super().__init__(*args, **kwargs)
        self._table = table
        self._cond = threading.Condition()
        self.done = False
        self.failed = False
        self.first_token: Optional[int] = None

    @property
    def streaming(self) -> bool:
        return True

    @property
    def total_pages(self) -> int:
        return -(-self.prompt_len // self.layout.page_size)

    def append_pages(self, page_ids: list[int]) -> None:
        with self._cond:
            if self.done or self.failed:
                # Terminal: finish pinned the final page list (appending
                # would corrupt it) or fail released the pages (appending
                # would advertise freed — possibly reused — pages to the
                # puller). Late chunk completions just drop.
                return
            conformance.observe("kv_stream_transfer", self.transfer_id,
                                "append")
            self.page_ids.extend(int(p) for p in page_ids)
            self._cond.notify_all()

    def finish(self, first_token: int, all_page_ids: list[int]) -> None:
        """Prompt pass complete: pin the final page list (including the
        partial last page) and publish the first sampled token. The TTL
        clock restarts HERE — it started at the first chunk, and a
        prompt that legitimately prefilled longer than ttl_secs must not
        become expirable the instant it completes (racing a decode pull
        that is still being retried)."""
        with self._cond:
            if self.failed or self.done:
                # fail() already released the pages (a cancel racing the
                # final chunk): resurrecting done=True here would restart
                # the TTL and hand the puller page ids the pool may have
                # reissued. A repeated finish must not restart the TTL
                # either. First terminal event wins.
                return
            conformance.observe("kv_stream_transfer", self.transfer_id,
                                "finish")
            self.page_ids = [int(p) for p in all_page_ids]
            self.first_token = int(first_token)
            self.done = True
            self.created_at = time.monotonic()
            self._cond.notify_all()

    def fail(self) -> None:
        """Prefill died mid-stream (cancel/error): wake waiters with the
        failure and release the pages iff no puller claimed the entry."""
        with self._cond:
            if self.done or self.failed:
                # done: the prompt pass COMPLETED before the cancel
                # landed — the parked pages are a valid, pullable
                # transfer and the TTL (restarted by finish) owns their
                # release; aborting now would yank a healthy handoff out
                # from under a decode pull. failed: already released.
                return
            conformance.observe("kv_stream_transfer", self.transfer_id,
                                "fail")
            self.failed = True
            self._cond.notify_all()
        if self._table.claim(self.transfer_id) is not None:
            # We won the claim: no puller will ever release — we must.
            self.release()

    def wait_ready(self, have: int, timeout: float
                   ) -> tuple[list[int], bool, bool]:
        """Block until more than `have` pages are parked, the transfer is
        done, or it failed. Returns (page_ids snapshot, done, failed);
        a timeout returns the unchanged snapshot (caller re-checks its
        deadline and loops)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while (len(self.page_ids) <= have and not self.done
                   and not self.failed):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=min(0.2, remaining))
            return list(self.page_ids), self.done, self.failed


class PendingTransferTable:
    """Prefill-side registry of sequences awaiting pull. Entries hold their
    pages pinned until pulled or expired (the reference leans on engine-side
    kv_transfer timeouts the same way).

    Thread-safe: `add` runs on the engine scheduler thread while pulls and
    TTL expiry run on the event loop. A pull `claim`s its entry (removing it
    atomically) so expiry can never release pages a gather is reading; the
    claimer owns exactly one release."""

    def __init__(self, ttl_secs: float = 120.0) -> None:
        self.ttl_secs = ttl_secs
        self._table: dict[str, PendingTransfer] = {}
        self._lock = threading.Lock()

    def add(self, transfer: PendingTransfer) -> None:
        with self._lock:
            self._table[transfer.transfer_id] = transfer

    def claim(self, transfer_id: str) -> Optional[PendingTransfer]:
        """Atomically take ownership of an entry (pull path). The caller
        must call `.release()` exactly once when done with the pages."""
        with self._lock:
            return self._table.pop(transfer_id, None)

    def expire_stale(self) -> int:
        now = time.monotonic()
        with self._lock:
            # A streaming transfer whose prompt pass is still running is
            # never stale: its pages belong to a live sequence (releasing
            # them mid-prefill would hand them to another request). Abort
            # is the scheduler's job (the on_prefill_chunk(None) hook).
            stale = [tid for tid, t in self._table.items()
                     if now - t.created_at > self.ttl_secs
                     and not (t.streaming and not getattr(t, "done", True))]
            claimed = [self._table.pop(tid) for tid in stale]
        for transfer in claimed:
            transfer.release()
        return len(claimed)

    def expire_all(self) -> int:
        """Force-expire every unclaimed entry (graceful-drain deadline:
        parked handoff pages a peer never pulled must release before
        the worker deregisters). Claims atomically like expire_stale,
        so a pull racing this can never double-release. Live streaming
        transfers degrade through their release hook's cancel path."""
        with self._lock:
            claimed = list(self._table.values())
            self._table.clear()
        for transfer in claimed:
            transfer.release()
        return len(claimed)

    def __len__(self) -> int:
        with self._lock:
            return len(self._table)


def encode_block_chunks(
    blocks: np.ndarray,  # [n, L, 2, ps, kh, hd] universal layout
    layout: KvLayoutDescriptor,
    base: int = 0,
    total_pages: Optional[int] = None,
) -> Iterator[dict]:
    """Chunk a block bundle into wire frames: msgpack dicts with raw bytes.
    Chunk size targets TRANSFER_CHUNK_BYTES so large prompts stream instead
    of building one giant frame.

    Streaming handoffs (docs/disaggregation.md) encode SLICES of the full
    transfer as chunks become ready: `base` is the absolute page offset of
    this bundle and `total_pages` the final page count — the assembler
    then tracks completeness by pages instead of chunk count (the chunk
    count is unknowable while prefill is still running)."""
    n = blocks.shape[0]
    pages_per_chunk = max(1, TRANSFER_CHUNK_BYTES // max(1, layout.page_bytes()))
    total_chunks = -(-n // pages_per_chunk)
    for ci in range(total_chunks):
        lo = ci * pages_per_chunk
        hi = min(n, lo + pages_per_chunk)
        part = np.ascontiguousarray(blocks[lo:hi])
        frame = {
            "chunk": ci,
            "total_chunks": total_chunks,
            "page_start": base + lo,
            "page_count": hi - lo,
            "layout": layout.to_wire(),
            "data": part.tobytes(),
        }
        if total_pages is not None:
            frame["total_pages"] = total_pages
        yield frame


class BlockAssembler:
    """Decode-side reassembly of pulled chunks into one bundle array.
    Completeness: `total_pages` frames (streaming handoff) complete when
    every page arrived; classic frames complete at `total_chunks` frames."""

    def __init__(self) -> None:
        self._chunks: dict[int, tuple[int, int, bytes]] = {}  # by page_start
        self._layout: Optional[KvLayoutDescriptor] = None
        self._total: Optional[int] = None
        self._total_pages: Optional[int] = None

    def add(self, frame: dict) -> None:
        layout = KvLayoutDescriptor.from_wire(frame["layout"])
        if self._layout is None:
            self._layout = layout
        elif not self._layout.compatible(layout):
            raise ValueError("layout changed mid-transfer")
        if frame.get("total_pages") is not None:
            self._total_pages = int(frame["total_pages"])
        else:
            self._total = frame["total_chunks"]
        self._chunks[frame["page_start"]] = (
            frame["page_start"], frame["page_count"], frame["data"]
        )

    @property
    def pages(self) -> int:
        return sum(c[1] for c in self._chunks.values())

    @property
    def complete(self) -> bool:
        if self._total_pages is not None:
            return self.pages >= self._total_pages
        return self._total is not None and len(self._chunks) == self._total

    def assemble(self) -> tuple[np.ndarray, KvLayoutDescriptor]:
        if not self.complete:
            raise ValueError("transfer incomplete")
        layout = self._layout
        shape_tail = (layout.n_layers, 2, layout.page_size, layout.kv_heads,
                      layout.head_dim)
        n = self.pages
        out = np.empty((n,) + shape_tail, np.dtype(layout.dtype))
        for start, count, data in self._chunks.values():
            out[start : start + count] = np.frombuffer(
                data, np.dtype(layout.dtype)
            ).reshape((count,) + shape_tail)
        return out, layout
