"""Token-level engine pipeline operators.

Everything in the request path implements one interface — `generate(request)
-> async stream` — mirroring the reference's core invariant that every hop is
an AsyncEngine (ref: lib/runtime/src/engine.rs:201-213) and pipelines compose
by linking operators (ref: entrypoint/input/common.rs:224 build_routed_pipeline):

    Preprocessor -> Migration -> [KvRouterEngine | RouterEngine] -> worker

Operators here speak PreprocessedRequest/EngineOutput; HTTP-shape conversion
lives in preprocessor.py; transport in runtime.push_router.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import AsyncIterator, Optional

from ..kv_router import KvScheduler, WorkerWithDpRank
from ..runtime.flight_recorder import get_recorder
from ..runtime.logging import get_logger
from ..runtime.metrics import DEADLINE_EXCEEDED, SESSION_AFFINITY
from ..runtime.otel import get_tracer
from ..runtime.push_router import NoInstancesAvailable, PushRouter
from ..runtime.request_plane import ConnectionLost, RemoteError
from ..runtime.resilience import RetryPolicy
from ..tokens import compute_block_hashes
from .protocols import EngineOutput, PreprocessedRequest

log = get_logger("llm.engine")


class TokenEngine:
    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[EngineOutput]:
        raise NotImplementedError
        yield  # pragma: no cover


class RouterEngine(TokenEngine):
    """Dispatch to workers through a PushRouter (round_robin/random/p2c).

    `lora_instances(name)` (optional) returns the instance ids currently
    advertising a LoRA adapter; adapter requests only route there (ref:
    lora.rs — adapters are a routing constraint, not just a name)."""

    def __init__(self, router: PushRouter, lora_instances=None) -> None:
        self.router = router
        self._lora_instances = lora_instances

    def _allowed(self, request: PreprocessedRequest) -> Optional[set]:
        if not request.lora_name or self._lora_instances is None:
            return None
        allowed = self._lora_instances(request.lora_name)
        if not allowed:
            raise NoInstancesAvailable(
                f"no instance has adapter {request.lora_name!r}")
        return allowed

    async def generate(self, request: PreprocessedRequest) -> AsyncIterator[EngineOutput]:
        async for item in self.router.generate(
                request.to_wire(), instance_id=_pinned_instance(request),
                allowed=self._allowed(request), deadline=request.deadline,
                traceparent=_traceparent_of(request)):
            yield EngineOutput.from_wire(item)


def _traceparent_of(request: PreprocessedRequest) -> Optional[str]:
    """The trace context the frontend stamped on the request; every
    pipeline operator parents its spans under it."""
    return (request.annotations or {}).get("traceparent")


def _pinned_instance(request: PreprocessedRequest) -> Optional[int]:
    """Instance id pinned by an external endpoint picker via the gateway
    header contract (annotation set in http_service from
    x-worker-instance-id; hex as logged/returned by the EPP)."""
    raw = (request.annotations or {}).get("target_instance")
    if not raw:
        return None
    try:
        return int(str(raw), 16)
    except ValueError:
        log.warning("bad target_instance annotation %r; ignoring", raw)
        return None


def _unpin(request: PreprocessedRequest) -> PreprocessedRequest:
    """Drop a gateway pin (`target_instance` annotation) from a
    migration re-dispatch. The pinned worker just failed or announced
    departure, and every routed mode vetoes unavailable explicit
    targets (PushRouter._pick) — keeping the pin would burn the whole
    migration budget re-dialing a worker that will never come back and
    surface a spurious client error. The EPP's placement decision is
    invalidated by the departure; the replay leg re-selects."""
    ann = request.annotations
    if not ann or "target_instance" not in ann:
        return request
    return dataclasses.replace(
        request,
        annotations={k: v for k, v in ann.items()
                     if k != "target_instance"})


def _priority_of(request: PreprocessedRequest) -> float:
    """Optional per-request priority bump for the admission queue (the
    reference's priority_jump, carried as an annotation here)."""
    try:
        return float((request.annotations or {}).get("priority", 0.0))
    except (TypeError, ValueError):
        return 0.0


class KvRouterEngine(TokenEngine):
    """KV-aware dispatch: block-hash the prompt, score candidates by cached
    overlap + load, route direct, and track the request lifecycle
    (ref: lib/llm/src/kv_router.rs KvRouter + push_router.rs KvPushRouter;
    flow in section 3.3). When the admission queue is enabled
    (DYNT_ROUTER_QUEUE_THRESHOLD >= 0), saturation parks requests in
    fcfs/lcfs/wspt order instead of routing immediately
    (ref: lib/kv-router/src/scheduling/queue.rs)."""

    def __init__(self, router: PushRouter, scheduler: KvScheduler,
                 lora_instances=None, queue=None, session=None) -> None:
        from ..kv_router.queue import SchedulerQueue
        from ..runtime.config import env

        self.router = router
        self.scheduler = scheduler
        self._lora_instances = lora_instances
        # Session tier (dynamo_tpu/session.SessionTier): residency
        # lookups before selection, routed-worker observations after.
        self.session = session
        if queue is None:
            threshold = env("DYNT_ROUTER_QUEUE_THRESHOLD")
            budget = env("DYNT_MAX_BATCHED_TOKENS")
            queue = SchedulerQueue(
                scheduler,
                threshold_frac=threshold if threshold >= 0 else None,
                policy=env("DYNT_ROUTER_QUEUE_POLICY"),
                max_batched_tokens=(
                    (lambda w: budget) if budget > 0 else None),
            )
        self.queue = queue

    async def generate(self, request: PreprocessedRequest) -> AsyncIterator[EngineOutput]:
        from ..kv_router.queue import QueuedRequest

        await self.router.client.start()
        traceparent = _traceparent_of(request)
        pinned_instance = _pinned_instance(request)
        if pinned_instance is not None:
            # External endpoint picker owns placement (gateway EPP header
            # contract): direct route, no booking — the picker's view of
            # load already includes this request.
            async for item in self.router.generate(
                    request.to_wire(), instance_id=pinned_instance,
                    deadline=request.deadline, traceparent=traceparent):
                yield EngineOutput.from_wire(item)
            return
        avail = self.router.available()
        pinned = False
        if request.lora_name and self._lora_instances is not None:
            has = self._lora_instances(request.lora_name)
            avail = [i for i in avail if i in has]
            # Adapter-constrained requests bypass the admission gate, like
            # the reference's allowed_worker_ids escape hatch (queue.rs
            # enqueue).
            pinned = True
        if not avail:
            raise NoInstancesAvailable(self.router.client.endpoint.subject)
        block_hashes = compute_block_hashes(
            request.token_ids, self.scheduler.config.block_size,
            lora_id=request.kv_salt(),
        )
        candidates = [WorkerWithDpRank(iid) for iid in avail]
        request_id = request.request_id
        # Router-selection span: queue wait (saturation parking) plus the
        # KV-match verdict — which worker won and at what cached overlap.
        sspan = get_tracer().start_span(
            "router.schedule", parent=traceparent,
            **{"request.id": request_id, "candidates": len(candidates)})
        # Cache-residency routing (session tier): a live session's
        # resident worker gets the affinity bonus in the selector; the
        # routed decision is observed back so the NEXT turn knows where
        # this one's KV landed.
        affinity = (self.session.residency(request.session_id)
                    if self.session is not None and request.session_id
                    else None)
        try:
            # schedule() books the request into the slot tracker
            # (add_request) as part of the decision, so a drained backlog
            # can't dogpile.
            result = await self.queue.schedule(QueuedRequest(
                candidates=candidates,
                block_hashes=block_hashes,
                isl_tokens=len(request.token_ids),
                priority_jump=_priority_of(request),
                pinned=pinned,
                request_id=request_id,
                deadline=request.deadline,
                affinity_worker=affinity,
                priority_class=request.priority,
                tenant=request.tenant,
            ))
            sspan.set_attribute("worker.instance",
                                f"{result.worker.worker_id:x}")
            sspan.set_attribute("kv.overlap_blocks", result.overlap_blocks)
            sspan.set_attribute("router.logit", float(result.logit))
            if self.session is not None and request.session_id:
                outcome = ("none" if affinity is None else
                           "hit" if result.worker.worker_id == affinity
                           else "miss")
                SESSION_AFFINITY.labels(outcome=outcome).inc()
                sspan.set_attribute("session.affinity", outcome)
                self.session.observe_routed(request.session_id,
                                            result.worker.worker_id)
            sspan.end(ok=True)
        finally:
            # Cancelled/errored while parked: close the span so queue
            # waits that never scheduled still show up in the trace.
            sspan.end(ok=False)
        first = True
        try:
            async for item in self.router.generate(
                request.to_wire(), instance_id=result.worker.worker_id,
                deadline=request.deadline, traceparent=traceparent,
            ):
                if first:
                    self.scheduler.mark_prefill_completed(request_id)
                    self.queue.update()
                    first = False
                yield EngineOutput.from_wire(item)
        finally:
            self.scheduler.free(request_id)
            self.queue.update()


class MultimodalEngine(TokenEngine):
    """Resolve a request's images through the encoder pool (the E stage of
    E/P/D) and attach the embeddings before the request hits prefill/
    decode routing. No encoder pool -> explicit error (a silently dropped
    image would produce confident answers about an image the model never
    saw)."""

    def __init__(self, inner: TokenEngine, pool_lookup) -> None:
        self.inner = inner
        self._pool_lookup = pool_lookup

    async def generate(self, request: PreprocessedRequest) -> AsyncIterator[EngineOutput]:
        urls = request.annotations.get("media_urls")
        if urls and request.media_embeddings is None:
            from ..multimodal import encode_via_pool

            pool = self._pool_lookup()
            if pool is None or not pool.instances:
                yield EngineOutput(
                    finish_reason="error",
                    error="multimodal request but no encoder workers are "
                          "registered for this model")
                return
            rows = await encode_via_pool(pool.router, urls)
            if rows is None:
                yield EngineOutput(finish_reason="error",
                                   error="image encoding failed")
                return
            request.media_embeddings = {
                "shape": list(rows.shape),
                "data": rows.astype("float32").tobytes(),
            }
            # The multi-MB data URLs have served their purpose — shipping
            # them to the worker alongside the embeddings would roughly
            # double the wire payload. Keep a count for observability.
            request.annotations = {
                **{k: v for k, v in request.annotations.items()
                   if k != "media_urls"},
                "media": len(urls),
            }
        async for output in self.inner.generate(request):
            yield output


class CooperativeMigration(ConnectionLost):
    """In-band `finish_reason="migrate"` from a worker: a PLANNED
    hand-off (elastic reshard, QoS preemption without a local park
    slot, graceful drain), not a failure. Bounded separately from
    failure migrations (DYNT_PREEMPT_MIGRATION_LIMIT vs
    migration_limit) and replayed without backoff jitter — the worker
    asked us to move, nothing is broken, and sleeping would only
    stretch the client's stall.

    A graceful-drain handoff frame (engine/drain.py) additionally
    carries `kv_transfer_params` with the pull route + resume state:
    the replay dispatches with those as `disaggregated_params`, so the
    destination PULLS the source's computed KV and resumes the stream
    bit-identically instead of re-prefilling prompt+generated. Clean
    handoff hops do NOT consume the cooperative bound — a rolling
    restart of N workers legitimately hops a long stream N times, and
    a failed hop degrades to a plain migrate which does consume it."""

    def __init__(self, reason: str,
                 kv_transfer_params: Optional[dict] = None) -> None:
        super().__init__(reason)
        self.kv_transfer_params = kv_transfer_params


class Migration(TokenEngine):
    """Retry a broken stream on another worker, preserving generated tokens
    (ref: lib/llm/src/migration.rs:36 — accumulated tokens are replayed so
    decode continues where it left off; bounded by migration_limit AND the
    request's end-to-end deadline: every replay consumes the remaining
    budget — propagated down through the router's headers — instead of a
    fresh flat timeout, and backoff between replays is jittered by a
    RetryPolicy). Worker-initiated cooperative migrations (in-band
    `finish_reason="migrate"`) carry their own bound (`cooperative_limit`,
    DYNT_PREEMPT_MIGRATION_LIMIT) and skip the backoff — a planner/QoS
    decision to move a sequence must not consume the failure budget that
    protects against crash loops."""

    def __init__(self, inner: TokenEngine, migration_limit: int = 3,
                 retry_policy: Optional[RetryPolicy] = None,
                 cooperative_limit: Optional[int] = None) -> None:
        from ..runtime.config import env

        self.inner = inner
        self.migration_limit = migration_limit
        self.cooperative_limit = (env("DYNT_PREEMPT_MIGRATION_LIMIT")
                                  if cooperative_limit is None
                                  else cooperative_limit)
        self.policy = retry_policy or RetryPolicy.from_env()

    async def generate(self, request: PreprocessedRequest) -> AsyncIterator[EngineOutput]:
        generated: list[int] = []
        attempts = 0
        coop_attempts = 0
        handoff_hops = 0
        prev_delay: Optional[float] = None
        current = request
        while True:
            try:
                async for output in self.inner.generate(current):
                    if output.finish_reason == "migrate":
                        # In-band migration request from the worker (e.g.
                        # elastic reshard, QoS preemption, graceful
                        # drain): retry like a broken stream, tokens
                        # preserved, but on the COOPERATIVE bound. Never
                        # reaches the client. A drain handoff frame also
                        # carries the KV pull route + resume state.
                        raise CooperativeMigration(
                            output.error or "worker requested migration",
                            kv_transfer_params=output.kv_transfer_params)
                    if current.prior_output_tokens \
                            and output.prompt_tokens is not None:
                        # The replayed prompt embeds the tokens already
                        # generated (and already billed as completion);
                        # report the ORIGINAL prompt length, or usage
                        # accounting double-counts across a migration.
                        output.prompt_tokens = max(
                            0, output.prompt_tokens
                            - len(current.prior_output_tokens))
                    generated.extend(output.token_ids)
                    yield output
                return
            except (ConnectionLost, NoInstancesAvailable, asyncio.TimeoutError) as exc:
                cooperative = isinstance(exc, CooperativeMigration)
                handoff = (exc.kv_transfer_params
                           if cooperative
                           and exc.kv_transfer_params is not None
                           and exc.kv_transfer_params.get("handoff")
                           is not None else None)
                if handoff is not None:
                    # A clean drain handoff does NOT consume the
                    # cooperative replay budget: each hop is driven by
                    # an actual worker departure (a rolling restart of
                    # N workers legitimately hops a long stream N
                    # times), and a failed hop comes back as a PLAIN
                    # migrate, which DOES consume it — so ping-pong is
                    # already bounded. The hard cap below only guards a
                    # pathological livelock.
                    handoff_hops += 1
                    if handoff_hops > 64:
                        log.warning("handoff hop cap reached for %s: %r",
                                    request.request_id, exc)
                        yield EngineOutput(
                            finish_reason="error",
                            error=f"migration limit exceeded: {exc}")
                        return
                elif cooperative:
                    coop_attempts += 1
                else:
                    attempts += 1
                if handoff is None and (
                        coop_attempts > self.cooperative_limit
                        if cooperative else
                        attempts > self.migration_limit):
                    log.warning("%smigration limit reached for %s: %r",
                                "cooperative " if cooperative else "",
                                request.request_id, exc)
                    yield EngineOutput(finish_reason="error",
                                       error=f"migration limit exceeded: {exc}")
                    return
                if request.deadline is not None and request.deadline.expired():
                    # No budget left to replay into: the client has
                    # already given up — surface the overrun instead of
                    # burning another worker slot.
                    DEADLINE_EXCEEDED.labels(component="migration").inc()
                    log.warning("deadline exceeded migrating %s: %r",
                                request.request_id, exc)
                    yield EngineOutput(
                        finish_reason="error",
                        error=f"deadline exceeded during migration: {exc}")
                    return
                if handoff is not None:
                    # Graceful-drain KV handoff (engine/drain.py;
                    # docs/fault-tolerance.md departure ladder rung 1):
                    # re-dispatch the SAME request (same prompt, same
                    # sampling — the resume state rides in the params)
                    # with the pull route as disaggregated_params. The
                    # destination pulls the source's computed pages and
                    # continues with the original sampler keys — zero
                    # re-prefilled tokens, bit-identical stream. A
                    # failed pull comes back as a PLAIN migrate, which
                    # lands on the replay rung below next iteration.
                    get_tracer().start_span(
                        "migration.handoff",
                        parent=_traceparent_of(request),
                        **{"request.id": request.request_id,
                           "attempt": handoff_hops,
                           "tokens.preserved": len(generated)}
                    ).end(ok=True)
                    get_recorder().event(
                        request.request_id, "migration",
                        attempt=handoff_hops, cooperative=True,
                        handoff=True, tokens_preserved=len(generated))
                    log.info("drain handoff for %s (hop %d, %d "
                             "tokens preserved, no re-prefill)",
                             request.request_id, handoff_hops,
                             len(generated))
                    current = _unpin(dataclasses.replace(
                        current, disaggregated_params=exc.kv_transfer_params))
                    await asyncio.sleep(0)  # planned move: no backoff
                    continue
                remaining = request.sampling.max_tokens - len(generated)
                if remaining <= 0:
                    yield EngineOutput(finish_reason="length")
                    return
                log.info("migrating %s (%sattempt %d, %d tokens preserved)",
                         request.request_id,
                         "cooperative " if cooperative else "",
                         coop_attempts if cooperative else attempts,
                         len(generated))
                # Replay marker on the trace + flight record: the worker
                # leg is being replaced, tokens preserved.
                get_tracer().start_span(
                    "migration.replay", parent=_traceparent_of(request),
                    **{"request.id": request.request_id,
                       "attempt": coop_attempts if cooperative else attempts,
                       "cooperative": cooperative,
                       "tokens.preserved": len(generated),
                       "cause": repr(exc)}).end(ok=True)
                get_recorder().event(request.request_id, "migration",
                                     attempt=(coop_attempts if cooperative
                                              else attempts),
                                     cooperative=cooperative,
                                     tokens_preserved=len(generated),
                                     cause=str(exc))
                sampling = type(request.sampling)(**{
                    **request.sampling.to_wire(), "max_tokens": remaining
                })
                # dataclasses.replace keeps EVERY other field — guided
                # processors, session pins, deadline, priority/tenant
                # (a replayed batch request must not sneak back in as
                # "standard") — while the replayed prompt embeds the
                # tokens already generated. A stale drain-handoff pull
                # route must NOT survive onto the replay leg: this rung
                # re-prefills instead.
                current = _unpin(dataclasses.replace(
                    request,
                    token_ids=list(request.token_ids) + generated,
                    sampling=sampling,
                    prior_output_tokens=list(generated),
                    disaggregated_params=None,
                ))
                if cooperative:
                    # Planned hand-off: replay immediately (yield once so
                    # the loop stays fair). Backoff exists to spread
                    # retry storms off a FAILING instance; a cooperative
                    # move has no failing instance to protect.
                    await asyncio.sleep(0)
                    continue
                delay = self.policy.next_delay(prev_delay)
                prev_delay = delay
                if request.deadline is not None:
                    delay = request.deadline.bound(delay)
                await asyncio.sleep(delay)
