"""Tokenizer abstraction: HF `tokenizers` (local files) + byte-level fallback.

The reference embeds HF tokenizers behind its preprocessor (ref: lib/llm/src/
preprocessor.rs uses the `tokenizers` crate; tokenizer config travels in the
ModelDeploymentCard). We support:

  * HfTokenizer  — loads tokenizer.json via the `tokenizers` library
  * ByteTokenizer — 256 byte vocab + special tokens; zero-asset, used for
    tests and the mocker (this environment has no model downloads)

Incremental (streaming) detokenization uses the prefix-offset technique: keep
decoding the tail window of tokens and only emit the stable UTF-8 suffix, so
multi-token unicode and SentencePiece prefix spaces render correctly.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence


class Tokenizer:
    eos_token_ids: list[int] = []
    vocab_size: int = 0
    chat_template: Optional[str] = None
    # How many trailing tokens may still merge with future tokens when
    # decoding incrementally (BPE merges / sentencepiece boundary spaces).
    # Byte-level decode is prefix-stable, so 0 there.
    stable_window: int = 4

    def encode(self, text: str) -> list[int]:
        raise NotImplementedError

    def decode(self, token_ids: Sequence[int]) -> str:
        raise NotImplementedError

    def token_text(self, token_id: int) -> Optional[str]:
        """Raw vocab string of one token (e.g. 'Ġhello', 'â' for a lone
        UTF-8 continuation byte under byte-level BPE), or None if
        unknown. Unlike decode(), never lossy: guided decoding inverts
        byte-level-BPE strings back to true bytes (llm/guided.py)."""
        return None

    def spec(self) -> dict:
        """Serializable description for the ModelDeploymentCard."""
        raise NotImplementedError


class ByteTokenizer(Tokenizer):
    """Byte-level: token i (< 256) is byte i. Special tokens above 255.
    Mirrors the role of the mocker's tokenizer-free operation."""

    BOS = 256
    EOS = 257
    PAD = 258
    IM_START = 259
    IM_END = 260

    SPECIALS = {BOS: "<s>", EOS: "</s>", PAD: "<pad>",
                IM_START: "<|im_start|>", IM_END: "<|im_end|>"}

    def __init__(self) -> None:
        self.eos_token_ids = [self.EOS, self.IM_END]
        self.vocab_size = 512  # headroom above 261 for model round numbers
        self.stable_window = 0

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8", errors="replace"))

    def decode(self, token_ids: Sequence[int]) -> str:
        out: list[str] = []
        buf = bytearray()
        for tok in token_ids:
            if tok < 256:
                buf.append(tok)
            else:
                if buf:
                    out.append(buf.decode("utf-8", errors="replace"))
                    buf.clear()
                out.append(self.SPECIALS.get(tok, f"<unk:{tok}>"))
        if buf:
            out.append(buf.decode("utf-8", errors="replace"))
        return "".join(out)

    def spec(self) -> dict:
        return {"kind": "byte"}


class HfTokenizer(Tokenizer):
    def __init__(self, path: str) -> None:
        from tokenizers import Tokenizer as _HfTok

        tok_file = path
        if os.path.isdir(path):
            tok_file = os.path.join(path, "tokenizer.json")
        self._tok = _HfTok.from_file(tok_file)
        self._path = path
        self.vocab_size = self._tok.get_vocab_size()
        self.eos_token_ids = []
        self.chat_template = None
        # Pull eos/chat_template from sibling config files if present.
        cfg_dir = path if os.path.isdir(path) else os.path.dirname(path)
        self._load_config(cfg_dir)

    def _load_config(self, cfg_dir: str) -> None:
        import json

        tcfg_path = os.path.join(cfg_dir, "tokenizer_config.json")
        gcfg_path = os.path.join(cfg_dir, "generation_config.json")
        if os.path.exists(tcfg_path):
            try:
                with open(tcfg_path) as f:
                    tcfg = json.load(f)
                self.chat_template = tcfg.get("chat_template")
                eos = tcfg.get("eos_token")
                if isinstance(eos, dict):
                    eos = eos.get("content")
                if isinstance(eos, str):
                    tid = self._tok.token_to_id(eos)
                    if tid is not None:
                        self.eos_token_ids.append(tid)
            except (OSError, ValueError):
                pass
        if os.path.exists(gcfg_path):
            try:
                with open(gcfg_path) as f:
                    gcfg = json.load(f)
                eos = gcfg.get("eos_token_id")
                if isinstance(eos, int):
                    eos = [eos]
                if isinstance(eos, list):
                    self.eos_token_ids.extend(
                        e for e in eos if e not in self.eos_token_ids
                    )
            except (OSError, ValueError):
                pass

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, token_ids: Sequence[int]) -> str:
        return self._tok.decode(list(token_ids), skip_special_tokens=True)

    def token_text(self, token_id: int) -> Optional[str]:
        return self._tok.id_to_token(token_id)

    def spec(self) -> dict:
        return {"kind": "hf", "path": self._path}


def load_tokenizer(spec: dict) -> Tokenizer:
    kind = spec.get("kind", "byte")
    if kind == "byte":
        return ByteTokenizer()
    if kind == "hf":
        return HfTokenizer(spec["path"])
    raise ValueError(f"unknown tokenizer spec: {spec!r}")


class IncrementalDetokenizer:
    """Streaming decode: emits only text that can no longer change as more
    tokens arrive (ref: Backend detokenizer hot loop, lib/llm/src/backend.rs).

    Per-token cost is O(window): we decode a sliding tail window anchored at
    `_ctx_start` and diff against the previously decoded length, instead of
    re-decoding the whole sequence (the reference's Rust hot loop does the
    same prefix-offset trick). The anchor slides forward periodically so the
    decoded span stays bounded."""

    # Keep this many already-stable tokens as decode context when sliding the
    # anchor (BPE/sentencepiece boundary effects cancel within the context).
    _CTX_KEEP = 16
    # Slide the anchor once the decoded span exceeds this many tokens.
    _CTX_MAX = 256

    def __init__(self, tokenizer: Tokenizer, window: Optional[int] = None) -> None:
        self._tok = tokenizer
        self._ids: list[int] = []
        self._window = tokenizer.stable_window if window is None else window
        self._ctx_start = 0  # decode-anchor token index
        self._stable_tokens = 0  # tokens whose text has been emitted
        self._prev_len = 0  # len(decode(ids[_ctx_start:_stable_tokens])) - held-back "�"

    def push(self, token_ids: Sequence[int]) -> str:
        """Add tokens, return newly-stable text (may be '')."""
        self._ids.extend(token_ids)
        n = len(self._ids)
        stable = n if self._window == 0 else max(0, n - self._window)
        if stable <= self._stable_tokens:
            return ""
        text = self._tok.decode(self._ids[self._ctx_start : stable])
        candidate = text[self._prev_len :]
        # Never emit a trailing replacement char (partial UTF-8 sequence);
        # it re-decodes complete once the rest of the char arrives.
        while candidate.endswith("�"):
            candidate = candidate[:-1]
        self._stable_tokens = stable
        self._prev_len += len(candidate)
        if stable - self._ctx_start > self._CTX_MAX:
            self._ctx_start = max(0, stable - self._CTX_KEEP)
            anchored = self._tok.decode(self._ids[self._ctx_start : stable])
            while anchored.endswith("�"):  # keep held-back partial chars held
                anchored = anchored[:-1]
            self._prev_len = len(anchored)
        return candidate

    def flush(self) -> str:
        """Emit everything outstanding (end of stream)."""
        full = self._tok.decode(self._ids[self._ctx_start :])
        out = full[self._prev_len :]
        self._prev_len = len(full)
        self._stable_tokens = len(self._ids)
        return out
