"""OpenAI-compatible HTTP frontend.

Routes (ref: lib/llm/src/http/service/openai.rs:1811-2191, service_v2.rs):
  POST /v1/chat/completions   (SSE streaming + aggregated)
  POST /v1/completions
  GET  /v1/models
  GET  /health, /live, /metrics
503 load shedding above a KV-usage busy threshold (ref: busy_threshold.rs);
client-disconnect propagates cancellation into the pipeline (ref:
http/service/disconnect.rs).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import AsyncIterator, Optional

from aiohttp import web

from ..runtime import metrics as rt_metrics
from ..runtime.logging import current_request_id, get_logger
from ..runtime.push_router import NoInstancesAvailable
from ..runtime.request_plane import RemoteError
from .manager import ModelEntry, ModelManager
from .preprocessor import DeltaGenerator, RequestError
from .protocols import EngineOutput, PreprocessedRequest

log = get_logger("llm.http")


def _error_body(status: int, message: str, err_type: str = "invalid_request_error") -> dict:
    return {"error": {"message": message, "type": err_type, "code": status}}


class HttpService:
    def __init__(
        self,
        manager: ModelManager,
        host: str = "0.0.0.0",
        port: int = 8000,
        busy_threshold: Optional[float] = None,
    ) -> None:
        self.manager = manager
        self.host = host
        self.port = port
        self.busy_threshold = busy_threshold
        self._runner: Optional[web.AppRunner] = None

    # -- helpers -----------------------------------------------------------

    def _lookup(self, model: str) -> ModelEntry:
        entry = self.manager.get(model)
        if entry is None:
            raise web.HTTPNotFound(
                text=json.dumps(_error_body(
                    404, f"model '{model}' not found", "model_not_found")),
                content_type="application/json",
            )
        return entry

    def _check_busy(self, entry: ModelEntry) -> None:
        """Shed load when every live worker is past the KV busy threshold
        (ref: busy_threshold.rs + KvWorkerMonitor). Uses published
        LoadMetrics usage, which flows in every router mode."""
        if self.busy_threshold is None:
            return
        usages = [
            entry.worker_usage[iid]
            for iid in entry.router.client.instance_ids()
            if iid in entry.worker_usage
        ]
        if usages and min(usages) >= self.busy_threshold:
            raise web.HTTPServiceUnavailable(
                text=json.dumps(_error_body(503, "service busy", "overloaded")),
                content_type="application/json",
            )

    # -- handlers ----------------------------------------------------------

    async def _models(self, _request: web.Request) -> web.Response:
        return web.json_response({
            "object": "list",
            "data": [
                {"id": card.name, "object": "model", "created": 0,
                 "owned_by": "dynamo_tpu"}
                for card in self.manager.list_models()
            ],
        })

    async def _health(self, _request: web.Request) -> web.Response:
        models = [c.name for c in self.manager.list_models()]
        return web.json_response(
            {"status": "healthy" if models else "no_models", "models": models}
        )

    async def _metrics(self, _request: web.Request) -> web.Response:
        return web.Response(body=rt_metrics.render(), content_type="text/plain",
                            charset="utf-8")

    async def _chat(self, request: web.Request) -> web.StreamResponse:
        return await self._completion_common(request, kind="chat")

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._completion_common(request, kind="completions")

    async def _completion_common(self, request: web.Request, kind: str) -> web.StreamResponse:
        try:
            body = await request.json()
        except (ValueError, UnicodeDecodeError):
            return web.json_response(_error_body(400, "invalid JSON body"), status=400)
        model = body.get("model", "")
        entry = self._lookup(model)
        self._check_busy(entry)
        try:
            if kind == "chat":
                preprocessed = entry.preprocessor.preprocess_chat(body)
            else:
                preprocessed = entry.preprocessor.preprocess_completions(body)
        except RequestError as exc:
            return web.json_response(_error_body(400, str(exc)), status=400)

        current_request_id.set(preprocessed.request_id)
        # Tool parsing activates only when the request declares tools (the
        # reference gates on request.tools the same way); reasoning parsing
        # follows the model card.
        card = entry.preprocessor.card
        delta_gen = DeltaGenerator(
            entry.preprocessor, preprocessed, kind=kind,
            tool_parser=(card.tool_parser if body.get("tools") else None),
            reasoning_parser=card.reasoning_parser,
        )
        stream = bool(body.get("stream", False))
        rt_metrics.INPUT_TOKENS.labels(model=model).observe(len(preprocessed.token_ids))
        if stream:
            return await self._stream_response(request, entry, preprocessed,
                                               delta_gen, body)
        return await self._aggregate_response(entry, preprocessed, delta_gen)

    @staticmethod
    def _count_request(model: str, status: str,
                       start: Optional[float] = None) -> None:
        """Frontend request counter + duration — the planner's num_req and
        concurrency signals (ref: http/service/metrics.rs request counts
        feeding the Planner)."""
        labels = dict(namespace="http", component="frontend", endpoint=model)
        rt_metrics.REQUESTS_TOTAL.labels(status=status, **labels).inc()
        if start is not None:
            rt_metrics.REQUEST_DURATION.labels(**labels).observe(
                max(0.0, time.monotonic() - start))

    async def _generate(
        self, entry: ModelEntry, preprocessed: PreprocessedRequest
    ) -> AsyncIterator[EngineOutput]:
        async for output in entry.engine.generate(preprocessed):
            yield output

    async def _aggregate_response(
        self, entry: ModelEntry, preprocessed: PreprocessedRequest,
        delta_gen: DeltaGenerator,
    ) -> web.Response:
        model = preprocessed.model
        start = time.monotonic()
        first_token_at: Optional[float] = None
        last_token_at: Optional[float] = None
        try:
            async for output in self._generate(entry, preprocessed):
                if output.token_ids:
                    now = time.monotonic()
                    if first_token_at is None:
                        first_token_at = now
                        rt_metrics.TTFT_SECONDS.labels(model=model).observe(
                            now - start)
                    elif last_token_at is not None:
                        rt_metrics.ITL_SECONDS.labels(model=model).observe(
                            (now - last_token_at)
                            / max(1, len(output.token_ids)))
                    last_token_at = now
                delta_gen.on_output(output)
                if output.error:
                    return web.json_response(
                        _error_body(502, output.error, "engine_error"), status=502)
        except NoInstancesAvailable:
            return web.json_response(
                _error_body(503, "no workers available", "overloaded"), status=503)
        except RemoteError as exc:
            return web.json_response(
                _error_body(502, str(exc), "engine_error"), status=502)
        rt_metrics.OUTPUT_TOKENS.labels(model=model).observe(
            delta_gen.completion_tokens)
        self._count_request(model, "ok", start)
        return web.json_response(delta_gen.final_response())

    async def _stream_response(
        self, request: web.Request, entry: ModelEntry,
        preprocessed: PreprocessedRequest, delta_gen: DeltaGenerator, body: dict,
    ) -> web.StreamResponse:
        model = preprocessed.model
        response = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "X-Request-Id": preprocessed.request_id,
            },
        )
        await response.prepare(request)
        start = time.monotonic()
        first_token_at: Optional[float] = None
        last_token_at: Optional[float] = None
        include_usage = bool(
            (body.get("stream_options") or {}).get("include_usage", False)
        )
        try:
            async for output in self._generate(entry, preprocessed):
                now = time.monotonic()
                if output.token_ids:
                    if first_token_at is None:
                        first_token_at = now
                        rt_metrics.TTFT_SECONDS.labels(model=model).observe(now - start)
                    elif last_token_at is not None:
                        rt_metrics.ITL_SECONDS.labels(model=model).observe(
                            (now - last_token_at) / max(1, len(output.token_ids)))
                    last_token_at = now
                for chunk in delta_gen.on_output(output):
                    await response.write(
                        f"data: {json.dumps(chunk)}\n\n".encode())
                if delta_gen.finish_reason is not None:
                    break
            if include_usage:
                usage_chunk = {"id": delta_gen.chunk_id,
                               "object": "chat.completion.chunk" if delta_gen.kind == "chat" else "text_completion",
                               "created": delta_gen.created, "model": model,
                               "choices": [], "usage": delta_gen.usage()}
                await response.write(f"data: {json.dumps(usage_chunk)}\n\n".encode())
            await response.write(b"data: [DONE]\n\n")
        except NoInstancesAvailable:
            await response.write(
                f"data: {json.dumps(_error_body(503, 'no workers available'))}\n\n".encode())
            await response.write(b"data: [DONE]\n\n")
        except RemoteError as exc:
            # Emit an OpenAI-shaped error event then terminate the stream
            # cleanly so SDK clients see a parseable failure, not a dropped
            # chunked read.
            await response.write(
                f"data: {json.dumps(_error_body(502, str(exc), 'engine_error'))}\n\n".encode())
            await response.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away: stop generating (cancellation propagates to
            # the worker through the request plane).
            log.info("client disconnected: %s", preprocessed.request_id)
            raise
        finally:
            rt_metrics.OUTPUT_TOKENS.labels(model=model).observe(
                delta_gen.completion_tokens)
            status = "ok" if delta_gen.finish_reason is not None else "error"
            self._count_request(model, status, start)
        await response.write_eof()
        return response

    # -- lifecycle ---------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_post("/v1/completions", self._completions)
        app.router.add_get("/v1/models", self._models)
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._health)
        app.router.add_get("/metrics", self._metrics)
        return app

    async def start(self) -> None:
        self._runner = web.AppRunner(self.build_app(), access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.info("OpenAI frontend listening on %s:%d", self.host, self.port)

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
