"""OpenAI-compatible HTTP frontend.

Routes (ref: lib/llm/src/http/service/openai.rs:1811-2191, service_v2.rs,
anthropic.rs:63):
  POST /v1/chat/completions   (SSE streaming + aggregated)
  POST /v1/completions
  POST /v1/embeddings
  POST /v1/messages           (Anthropic Messages API)
  POST /v1/responses          (OpenAI Responses API)
  GET  /v1/models
  GET  /health, /live, /metrics
503 load shedding above a KV-usage busy threshold (ref: busy_threshold.rs);
client-disconnect propagates cancellation into the pipeline (ref:
http/service/disconnect.rs).
"""

from __future__ import annotations

import asyncio
import base64
import json
import math
import time
import uuid
from typing import AsyncIterator, Optional

from aiohttp import web

from ..runtime import metrics as rt_metrics
from ..runtime.admission import AdmissionRefused, check_admission
from ..runtime.config import env
from ..runtime.flight_recorder import get_recorder
from ..runtime.metric_labels import bounded_label
from ..runtime.logging import (current_request_id, current_trace_id,
                               get_logger)
from ..runtime.otel import get_tracer, trace_id_of
from ..runtime.push_router import NoInstancesAvailable
from ..runtime.request_plane import RemoteError
from ..runtime.resilience import Deadline, DeadlineExceeded
from ..runtime.status import (
    debug_requests_response,
    metrics_response,
    profile_response,
)
from ..session.wire import (
    extract_cache_control,
    resolve_anchor_tokens,
    session_id_of,
    strip_cache_control,
)
from .manager import ModelEntry, ModelManager
from .preprocessor import DeltaGenerator, RequestError
from .protocols import (
    EngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
    new_request_id,
    now_unix,
)

log = get_logger("llm.http")


def _error_body(status: int, message: str, err_type: str = "invalid_request_error") -> dict:
    return {"error": {"message": message, "type": err_type, "code": status}}


def _trace_id_of(preprocessed: PreprocessedRequest) -> str:
    """Trace id carried on the request (empty when tracing is off) — the
    exemplar that links a latency observation back to its trace."""
    return trace_id_of(preprocessed.annotations.get("traceparent"))


class _SloObserver:
    """Per-request latency observer shared by the streaming and aggregate
    paths: TTFT/ITL histograms (with OpenMetrics trace_id exemplars), the
    flight-recorder first_token stamp, and the goodput verdict the
    planner consumes (dynamo_slo_good_total / dynamo_slo_requests_total;
    an unset target always passes)."""

    def __init__(self, preprocessed: PreprocessedRequest,
                 ttft_target_ms: float, itl_target_ms: float,
                 wait_estimator=None) -> None:
        self.model = preprocessed.model
        self.request_id = preprocessed.request_id
        # Per-class / per-tenant goodput attribution (the multi-tenant
        # QoS headline, docs/multi-tenancy.md).
        self.priority = preprocessed.priority or "standard"
        self.tenant = preprocessed.tenant or "untagged"
        trace_id = _trace_id_of(preprocessed)
        self.exemplar = {"trace_id": trace_id} if trace_id else None
        self.start = time.monotonic()
        self.first_at: Optional[float] = None
        self.last_at: Optional[float] = None
        self.itl_max = 0.0
        self.ttft_target_ms = ttft_target_ms
        self.itl_target_ms = itl_target_ms
        # Admission-loop drain signal (runtime/admission.py): a first
        # token means one request entered service — drained from the
        # pool's queue — which is the rate the queue-wait estimate
        # divides the published backlog by.
        self.wait_estimator = wait_estimator
        self._finalized = False

    def on_output(self, output: EngineOutput) -> None:
        if not output.token_ids:
            return
        now = time.monotonic()
        if self.first_at is None:
            self.first_at = now
            rt_metrics.TTFT_SECONDS.labels(model=self.model).observe(
                now - self.start, exemplar=self.exemplar)
            get_recorder().stamp(self.request_id, "first_token")
            if self.wait_estimator is not None:
                self.wait_estimator.observe_drained(1)
        elif self.last_at is not None:
            gap = now - self.last_at
            rt_metrics.ITL_SECONDS.labels(model=self.model).observe(
                gap / max(1, len(output.token_ids)), exemplar=self.exemplar)
            # Worst-token verdict uses the RAW gap: tokens inside one
            # chunk arrive together, so the chunk's first token waited
            # the whole gap — averaging would let a long stall hide
            # inside a large chunk and pass the DYNT_SLO_ITL_MS target.
            self.itl_max = max(self.itl_max, gap)
        self.last_at = now

    def finalize_from(self, delta_gen: DeltaGenerator) -> None:
        """Derive the goodput verdict from the terminal generator state:
        good means the stream reached a finish_reason and it wasn't
        "error". Defined once so the streaming and aggregate paths can
        never diverge on what counts as a good request."""
        self.finalize(ok=delta_gen.finish_reason is not None
                      and delta_gen.finish_reason != "error")

    def finalize(self, ok: bool) -> None:
        if self._finalized:
            return
        self._finalized = True
        rt_metrics.SLO_REQUESTS.labels(
            model=self.model, priority=self.priority,
            tenant=bounded_label("tenant", self.tenant)).inc()
        if not ok:
            return
        # An unset target always passes: a clean zero-token completion
        # (first_at None) only fails when a TTFT target is configured —
        # it never produced the first token that target is about.
        if self.ttft_target_ms and (
                self.first_at is None
                or (self.first_at - self.start) * 1e3 > self.ttft_target_ms):
            return
        if self.itl_target_ms and self.itl_max * 1e3 > self.itl_target_ms:
            return
        rt_metrics.SLO_GOOD.labels(
            model=self.model, priority=self.priority,
            tenant=bounded_label("tenant", self.tenant)).inc()


class HttpService:
    def __init__(
        self,
        manager: ModelManager,
        host: str = "0.0.0.0",
        port: int = 8000,
        busy_threshold: Optional[float] = None,
        audit=None,  # Optional[audit.AuditBus]
        recorder=None,  # Optional[audit.Recorder]
        runtime=None,  # Optional[DistributedRuntime]: admin fan-out routes
        slo_ttft_ms: Optional[float] = None,
        slo_itl_ms: Optional[float] = None,
    ) -> None:
        self.manager = manager
        self.host = host
        self.port = port
        self.busy_threshold = busy_threshold
        # Goodput targets for dynamo_slo_good_total (0 = no requirement);
        # the frontend CLI flags override the DYNT_SLO_* env defaults.
        self.slo_ttft_ms = (env("DYNT_SLO_TTFT_MS") if slo_ttft_ms is None
                            else slo_ttft_ms)
        self.slo_itl_ms = (env("DYNT_SLO_ITL_MS") if slo_itl_ms is None
                           else slo_itl_ms)
        # Per-model overrides set at runtime via POST /busy_threshold
        # (ref: busy_threshold.rs); the constructor value is the default.
        self.busy_thresholds: dict[str, float] = {}
        self.audit = audit
        self.recorder = recorder
        self.runtime = runtime
        self._runner: Optional[web.AppRunner] = None

    # -- helpers -----------------------------------------------------------

    def _lookup(self, model: str) -> tuple[ModelEntry, Optional[str]]:
        """Resolve a model OR adapter name to (entry, lora_name). Resolved
        exactly once per request — re-resolving later could silently fall
        back to the base model if the adapter is unloaded concurrently."""
        entry, lora = self.manager.resolve(model)
        if entry is None:
            raise web.HTTPNotFound(
                text=json.dumps(_error_body(
                    404, f"model '{model}' not found", "model_not_found")),
                content_type="application/json",
            )
        return entry, lora

    def _retry_after(self, entry: Optional[ModelEntry]) -> str:
        """Retry-After seconds for 503 shed responses: the estimated
        drain time of the model pool's queue (runtime/admission.py),
        floored/capped by the DYNT_RETRY_AFTER_MIN/MAX_SECS knobs — an
        honest hint instead of the old fixed constant. Integer per
        RFC 9110 (ceil so the client never retries a hair early)."""
        if entry is None:
            return str(max(1, int(env("DYNT_RETRY_AFTER_MIN_SECS"))))
        est = entry.wait_estimator
        secs = est.retry_after_s(est.estimate_wait_ms(extra=1))
        return str(max(1, math.ceil(secs)))

    def _check_busy(self, entry: ModelEntry) -> None:
        """Shed load when every live worker is past the KV busy threshold
        (ref: busy_threshold.rs + KvWorkerMonitor). Uses published
        LoadMetrics usage, which flows in every router mode."""
        threshold = self.busy_thresholds.get(entry.card.name,
                                             self.busy_threshold)
        if threshold is None:
            return
        usages = [
            entry.worker_usage[iid]
            for iid in entry.router.client.instance_ids()
            if iid in entry.worker_usage
        ]
        if usages and min(usages) >= threshold:
            rt_metrics.REQUESTS_SHED.labels(reason="busy").inc()
            raise web.HTTPServiceUnavailable(
                text=json.dumps(_error_body(503, "service busy", "overloaded")),
                content_type="application/json",
                headers={"Retry-After": self._retry_after(entry)},
            )

    def _admit_deadline(self, request: web.Request,
                        entry: Optional[ModelEntry] = None,
                        ) -> Optional[Deadline]:
        """Derive the request's end-to-end Deadline: an upstream-propagated
        x-dynt-deadline-ms header wins; otherwise DYNT_DEADLINE_SECS (0
        disables). A budget already spent on arrival is shed immediately
        with 503 + Retry-After — dispatching it would occupy a worker for
        a client that has already timed out ('The Tail at Scale'
        admission control)."""
        # HTTP headers are case-insensitive; Deadline.from_wire keys are
        # canonical lowercase.
        deadline = Deadline.from_wire(
            {k.lower(): v for k, v in request.headers.items()})
        if deadline is None:
            budget = env("DYNT_DEADLINE_SECS")
            if budget and budget > 0:
                deadline = Deadline(budget)
        if deadline is not None and deadline.expired():
            rt_metrics.REQUESTS_SHED.labels(reason="deadline").inc()
            raise web.HTTPServiceUnavailable(
                text=json.dumps(_error_body(
                    503, "request deadline already spent", "overloaded")),
                content_type="application/json",
                headers={"Retry-After": self._retry_after(entry)},
            )
        return deadline

    @staticmethod
    def _refused_503(exc: AdmissionRefused) -> web.HTTPServiceUnavailable:
        """The ONE AdmissionRefused -> 503 translation (body shape +
        integer Retry-After) every pre-dispatch admission edge raises."""
        return web.HTTPServiceUnavailable(
            text=json.dumps(_error_body(503, str(exc), "overloaded")),
            content_type="application/json",
            headers={"Retry-After": str(max(1, math.ceil(
                exc.retry_after_s)))},
        )

    def _check_queue_admission(self, entry: ModelEntry,
                               deadline: Optional[Deadline],
                               tenant: str = "") -> None:
        """Deadline-aware admission (the shed-early rung of the
        degradation ladder, docs/fault-tolerance.md): refuse a request
        whose budget cannot survive the estimated queue wait of the
        model's pool — BEFORE preprocessing or dispatch burns any work
        on a reply the client will never wait for. The wait is the
        backlog AHEAD of this arrival (extra=0): an empty pool admits
        regardless of how slow the measured drain is."""
        try:
            check_admission(entry.wait_estimator, deadline, tenant=tenant)
        except AdmissionRefused as exc:
            raise self._refused_503(exc)

    @staticmethod
    def _tenant_of(request: web.Request, body: dict) -> str:
        """Tenant identity for shed attribution BEFORE preprocessing —
        same precedence as _fold_qos_headers (body wins over the
        header) and the same bound the preprocessor applies, so queue
        sheds and quota/goodput series always name the same tenant."""
        raw = body.get("tenant") or request.headers.get(
            "x-dynt-tenant-id") or ""
        return str(raw).strip()[:64]

    @staticmethod
    def _fold_qos_headers(request: web.Request, body: dict) -> dict:
        """Multi-tenant QoS wire surface (docs/multi-tenancy.md): the
        x-dynt-priority / x-dynt-tenant-id headers fold into the body
        fields the preprocessor normalizes. Body fields win on conflict
        (the more specific declaration). Shared by every completion-
        shaped endpoint."""
        pr = request.headers.get("x-dynt-priority")
        if pr and not body.get("priority"):
            body["priority"] = pr
        ten = request.headers.get("x-dynt-tenant-id")
        if ten and not body.get("tenant"):
            body["tenant"] = ten
        return body

    def _check_tenant_quota(self, entry: ModelEntry,
                            preprocessed: PreprocessedRequest) -> None:
        """Weighted fair-share admission (runtime/admission.py
        TenantLedger): refuse an over-share tenant under contention
        with 503 + Retry-After BEFORE dispatch. The entry edge — it
        deposits admitted token costs into the shared ledger the
        downstream (router queue / prefill) edges read. Contention =
        the pool's queue-wait estimate is non-zero (work is waiting)."""
        from ..runtime.admission import (
            check_tenant_admission,
            get_tenant_ledger,
        )

        tokens = (len(preprocessed.token_ids)
                  + preprocessed.sampling.max_tokens)
        contended = entry.wait_estimator.estimate_wait_ms() > 0
        try:
            check_tenant_admission(get_tenant_ledger(),
                                   preprocessed.tenant, tokens,
                                   contended=contended, observe=True)
        except AdmissionRefused as exc:
            raise self._refused_503(exc)

    def _session_prepare(self, request: web.Request,
                         body: dict) -> tuple[dict, Optional[str], list]:
        """Session-tier wire surface, shared by chat and messages:
        extract cache_control anchors + the session id, and strip the
        markers so the preprocessor sees a byte-identical unmarked
        request (the unpinned-fallback contract). Returns
        (clean_body, session_id, raw_anchors)."""
        if not env("DYNT_SESSION_ENABLE"):
            return body, None, []
        anchors = extract_cache_control(body)
        sid = session_id_of(body, request.headers)
        if anchors or sid or "cache_control" in body \
                or "session_id" in body:
            body = strip_cache_control(body)
        return body, sid, anchors

    def _session_register(self, entry: ModelEntry, preprocessed,
                          chat_messages, sid: Optional[str],
                          anchors_raw: list) -> None:
        """Resolve anchors to token prefixes, pin them into the ledger,
        and stamp the request — after preprocessing, before dispatch.
        Failures degrade to an unpinned request, never a 5xx: the
        session tier is an accelerator, not a dependency."""
        if entry.session is None or not (anchors_raw or sid):
            return
        try:
            preprocessed.session_id = sid
            anchors = []
            if anchors_raw and not preprocessed.media_hashes:
                # Multimodal prompts skip anchors: image-placeholder
                # splicing breaks the rendered-prefix <-> token-prefix
                # correspondence the resolution relies on.
                anchors = resolve_anchor_tokens(
                    entry.preprocessor, chat_messages, anchors_raw,
                    preprocessed.token_ids)
            preprocessed.cache_anchors = [n for n, _ in anchors]
            if anchors and anchors[-1][1]:
                # Carry the longest anchor's requested TTL to the worker
                # so its KVBM pin honors the client's lease, not the
                # system ceiling.
                preprocessed.cache_ttl = float(anchors[-1][1])
            pinned = entry.session.register_request(preprocessed, anchors)
            if anchors or sid:
                get_recorder().event(
                    preprocessed.request_id, "session",
                    pinned_blocks=len(pinned), anchors=len(anchors),
                    session=bool(sid))
        except Exception:  # noqa: BLE001 — degrade to unpinned
            log.exception("session registration failed for %s",
                          preprocessed.request_id)

    # -- handlers ----------------------------------------------------------

    async def _models(self, _request: web.Request) -> web.Response:
        data = [
            {"id": card.name, "object": "model", "created": 0,
             "owned_by": "dynamo_tpu"}
            for card in self.manager.list_models()
        ]
        data += [
            {"id": name, "object": "model", "created": 0,
             "owned_by": "dynamo_tpu", "parent": base}
            for name, base in self.manager.list_adapters()
        ]
        data += [
            {"id": name, "object": "model", "created": 0,
             "owned_by": "dynamo_tpu"}
            for name in sorted(self.manager.image_pools)
        ]
        return web.json_response({"object": "list", "data": data})

    async def _health(self, _request: web.Request) -> web.Response:
        models = [c.name for c in self.manager.list_models()]
        return web.json_response(
            {"status": "healthy" if models else "no_models", "models": models}
        )

    async def _metrics(self, request: web.Request) -> web.Response:
        return metrics_response(request)

    async def _debug_requests(self, request: web.Request) -> web.Response:
        return debug_requests_response(request)

    async def _debug_profile(self, request: web.Request) -> web.Response:
        return await profile_response(request)

    async def _chat(self, request: web.Request) -> web.StreamResponse:
        return await self._completion_common(request, kind="chat")

    async def _completions(self, request: web.Request) -> web.StreamResponse:
        return await self._completion_common(request, kind="completions")

    async def _completion_common(self, request: web.Request, kind: str) -> web.StreamResponse:
        arrival = time.time()
        try:
            body = await request.json()
        except (ValueError, UnicodeDecodeError):
            return web.json_response(_error_body(400, "invalid JSON body"), status=400)
        model = body.get("model", "")
        entry, lora = self._lookup(model)
        self._check_busy(entry)
        deadline = self._admit_deadline(request, entry)
        self._check_queue_admission(entry, deadline,
                                    tenant=self._tenant_of(request, body))
        sid, anchors_raw = None, []
        if kind == "chat":
            body, sid, anchors_raw = self._session_prepare(request, body)
        body = self._fold_qos_headers(request, body)
        pre_start = time.monotonic()
        try:
            if kind == "chat":
                preprocessed = entry.preprocessor.preprocess_chat(body)
            else:
                preprocessed = entry.preprocessor.preprocess_completions(body)
        except RequestError as exc:
            return web.json_response(_error_body(400, str(exc)), status=400)
        rt_metrics.STAGE_DURATION.labels(stage="preprocess",
                                         model=model).observe(
            time.monotonic() - pre_start)
        # Fair-share quota edge: after preprocessing (the token cost is
        # known), before any dispatch work.
        self._check_tenant_quota(entry, preprocessed)
        preprocessed.lora_name = lora
        preprocessed.deadline = deadline
        # W3C trace-context propagation + span export: the frontend opens a
        # SERVER span (child of any incoming traceparent) and re-injects
        # ITS OWN context into the request annotations, so worker spans
        # parent under it across the request plane (ref: logging.rs OTLP
        # init + Injector/Extractor propagation).
        span = get_tracer().start_span(
            "http.chat" if kind == "chat" else "http.completions",
            parent=request.headers.get("traceparent"),
            kind=2, **{"request.id": preprocessed.request_id,
                       "model": model,
                       "input.tokens": len(preprocessed.token_ids)})
        self._open_http_trace(request, preprocessed, span, received=arrival)
        if kind == "chat":
            # After the timeline opens so the `session` event lands in
            # the flight record; markers resolve against the flattened
            # message list preprocess_chat produced in place.
            self._session_register(entry, preprocessed,
                                   body.get("messages") or [], sid,
                                   anchors_raw)
        # Gateway EPP header contract: an external endpoint picker (e.g.
        # the gateway/ EPP service behind a standard K8s gateway) pins
        # routing via headers — x-worker-instance-id direct-routes the
        # decode/aggregated leg; x-prefill-instance-id the prefill leg
        # (ref: deploy/inference-gateway/epp +
        # lib/llm/src/kv_router/prefill_router/mod.rs:117-120).
        target = request.headers.get("x-worker-instance-id")
        if target:
            preprocessed.annotations["target_instance"] = target
        prefill_target = request.headers.get("x-prefill-instance-id")
        if prefill_target:
            preprocessed.annotations["prefill_instance"] = prefill_target
        current_request_id.set(preprocessed.request_id)
        # Everything from here runs under the span: setup failures export
        # it with ok=False via __exit__ — failing requests are exactly the
        # ones operators need spans for. An exception escaping before the
        # response paths run their own accounting must also close the
        # flight-recorder timeline (no-op when already finished), or the
        # entry sits phantom-inflight until stale eviction.
        return await self._finish_guard(
            preprocessed.request_id,
            self._completion_traced(
                request, entry, preprocessed, span, body, kind, model),
            span=span)

    async def _finish_guard(self, request_id: str, coro, span):
        """Escape guard shared by every completion-shaped endpoint: an
        exception before the stream helpers' own handlers are armed
        (e.g. a disconnect during response.prepare) must still close the
        flight-recorder timeline (no-op when the response path already
        closed it) — a client going away is normal teardown, not an
        error, so the recorder's cancelled status skips the WARNING
        dump. The endpoint's server span is entered here so an escaping
        exception exports it ok=False via __exit__; the response helpers
        end it with the real outcome first (first end() wins)."""
        try:
            with span:
                return await coro
        except (ConnectionResetError, asyncio.CancelledError):
            get_recorder().finish(request_id, "cancelled")
            raise
        except BaseException:
            get_recorder().finish(request_id, "error")
            raise

    def _open_http_trace(self, request: web.Request,
                         preprocessed: PreprocessedRequest, span,
                         received: Optional[float] = None) -> None:
        """Inject the server span's context into the request annotations
        (falling back to the client's header when export is disabled) and
        open the flight-recorder timeline. Shared by every
        completion-shaped endpoint; the span itself is created at the
        call site so the span-name registry sees a literal name.
        `received` backdates the timeline to handler entry so the
        tokenization cost (which precedes the request id) stays visible
        against the deadline budget."""
        tp = span.traceparent or request.headers.get("traceparent")
        if tp:
            preprocessed.annotations["traceparent"] = tp
        current_trace_id.set(_trace_id_of(preprocessed) or None)
        get_recorder().start(preprocessed.request_id,
                             model=preprocessed.model,
                             trace_id=_trace_id_of(preprocessed),
                             tenant=preprocessed.tenant,
                             received=received)

    async def _completion_traced(
        self, request: web.Request, entry: ModelEntry,
        preprocessed: PreprocessedRequest, span, body: dict, kind: str,
        model: str,
    ) -> web.StreamResponse:
        # Span ownership matches _messages/_responses: _finish_guard holds
        # `with span:` (close-on-escape); the response helpers end it with
        # the real outcome (first end() wins).
        if self.recorder is not None:
            self.recorder.record_request(preprocessed.request_id, kind,
                                         body)
        # Tool parsing activates only when the request declares tools
        # (the reference gates on request.tools the same way);
        # reasoning parsing follows the model card.
        card = entry.preprocessor.card
        delta_gen = DeltaGenerator(
            entry.preprocessor, preprocessed, kind=kind,
            tool_parser=(card.tool_parser if body.get("tools")
                         else None),
            reasoning_parser=card.reasoning_parser,
        )
        stream = bool(body.get("stream", False))
        rt_metrics.INPUT_TOKENS.labels(model=model).observe(
            len(preprocessed.token_ids))
        if stream:
            return await self._stream_response(request, entry,
                                               preprocessed, delta_gen,
                                               body, span)
        return await self._aggregate_response(entry, preprocessed,
                                              delta_gen, span)

    def _count_request(self, model: str, status: str,
                       start: Optional[float] = None, *,
                       preprocessed: Optional[PreprocessedRequest] = None,
                       delta_gen: Optional[DeltaGenerator] = None,
                       kind: str = "", request_id: Optional[str] = None,
                       prompt_tokens: Optional[int] = None) -> None:
        """Frontend request counter + duration — the planner's num_req and
        concurrency signals (ref: http/service/metrics.rs request counts
        feeding the Planner). Also emits the audit record (off hot path:
        emit is a queue put)."""
        labels = dict(namespace="http", component="frontend", endpoint=model)
        rt_metrics.REQUESTS_TOTAL.labels(status=status, **labels).inc()
        if start is not None:
            rt_metrics.REQUEST_DURATION.labels(**labels).observe(
                max(0.0, time.monotonic() - start))
        rid = (request_id if request_id is not None
               else preprocessed.request_id if preprocessed else None)
        if rid:
            # Close the flight-recorder timeline on EVERY outcome (no-op
            # when a more specific status — deadline_exceeded — already
            # finished it, or when this endpoint never opened one).
            get_recorder().finish(rid, status)
        if self.audit is not None:
            from .audit import AuditRecord

            self.audit.emit(AuditRecord(
                request_id=(request_id if request_id is not None
                            else preprocessed.request_id if preprocessed
                            else ""),
                model=model, kind=kind, status=status,
                lora=(preprocessed.lora_name if preprocessed else None),
                prompt_tokens=(prompt_tokens if prompt_tokens is not None
                               else len(preprocessed.token_ids)
                               if preprocessed else 0),
                completion_tokens=(delta_gen.completion_tokens
                                   if delta_gen else 0),
                finish_reason=(delta_gen.finish_reason if delta_gen else None),
                latency_ms=((time.monotonic() - start) * 1e3 if start else 0.0),
            ))

    async def _consume(
        self, entry: ModelEntry, preprocessed: PreprocessedRequest,
        delta_gen: DeltaGenerator, observe_latency: bool = False,
    ) -> Optional[web.Response]:
        """Drive the engine stream to completion through `delta_gen`.
        Returns an error Response, or None on success. Shared by every
        non-streaming handler so error mapping stays in one place."""
        obs = (_SloObserver(preprocessed, self.slo_ttft_ms, self.slo_itl_ms,
                            wait_estimator=entry.wait_estimator)
               if observe_latency else None)
        cancelled = False
        try:
            async for output in self._generate(entry, preprocessed):
                if obs is not None:
                    obs.on_output(output)
                delta_gen.on_output(output)
                if output.error:
                    return web.json_response(
                        _error_body(502, output.error, "engine_error"),
                        status=502)
        except asyncio.CancelledError:
            # Client abort: don't let it count against the goodput ratio
            # or dump the timeline as an error.
            cancelled = True
            get_recorder().finish(preprocessed.request_id, "cancelled")
            raise
        except NoInstancesAvailable:
            return web.json_response(
                _error_body(503, "no workers available", "overloaded"),
                status=503, headers={"Retry-After": "1"})
        except AdmissionRefused as exc:
            # Deadline-aware refusal from a downstream admission edge
            # (router queue / prefill router): same 503 + honest
            # Retry-After contract as the frontend's own check — the
            # shed was already counted where it was decided.
            get_recorder().finish(preprocessed.request_id, "shed")
            return web.json_response(
                _error_body(503, str(exc), "overloaded"), status=503,
                headers={"Retry-After": str(max(1, math.ceil(
                    exc.retry_after_s)))})
        except DeadlineExceeded as exc:
            rt_metrics.DEADLINE_EXCEEDED.labels(component="frontend").inc()
            get_recorder().finish(preprocessed.request_id,
                                  "deadline_exceeded")
            return web.json_response(
                _error_body(504, str(exc), "deadline_exceeded"), status=504)
        except RemoteError as exc:
            return web.json_response(
                _error_body(502, str(exc), "engine_error"), status=502)
        finally:
            if obs is not None and not cancelled:
                obs.finalize_from(delta_gen)
        return None

    async def _generate(
        self, entry: ModelEntry, preprocessed: PreprocessedRequest
    ) -> AsyncIterator[EngineOutput]:
        rec = self.recorder
        async for output in entry.engine.generate(preprocessed):
            if rec is not None:
                rec.record_output(preprocessed.request_id, output.to_wire())
                if output.finish_reason is not None:
                    rec.record_end(preprocessed.request_id,
                                   output.finish_reason)
            yield output

    async def _aggregate_response(
        self, entry: ModelEntry, preprocessed: PreprocessedRequest,
        delta_gen: DeltaGenerator, span,
    ) -> web.Response:
        model = preprocessed.model
        start = time.monotonic()
        status = "error"
        try:
            err = await self._consume(entry, preprocessed, delta_gen,
                                      observe_latency=True)
            if err is not None:
                return err
            rt_metrics.OUTPUT_TOKENS.labels(model=model).observe(
                delta_gen.completion_tokens)
            status = "ok"
            return web.json_response(delta_gen.final_response())
        finally:
            # Counts + audit on EVERY outcome (error returns included) so
            # the audit trail never undercounts failures; the server span
            # must export ERROR for error Responses too, not just raises
            # (first end() wins over the enclosing `with span:`).
            span.end(ok=status == "ok")
            self._count_request(model, status, start,
                                preprocessed=preprocessed,
                                delta_gen=delta_gen, kind=delta_gen.kind)

    async def _stream_response(
        self, request: web.Request, entry: ModelEntry,
        preprocessed: PreprocessedRequest, delta_gen: DeltaGenerator,
        body: dict, span,
    ) -> web.StreamResponse:
        model = preprocessed.model
        response = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "X-Request-Id": preprocessed.request_id,
            },
        )
        await response.prepare(request)
        start = time.monotonic()
        obs = _SloObserver(preprocessed, self.slo_ttft_ms, self.slo_itl_ms,
                           wait_estimator=entry.wait_estimator)
        disconnected = False
        include_usage = bool(
            (body.get("stream_options") or {}).get("include_usage", False)
        )
        try:
            async for output in self._generate(entry, preprocessed):
                obs.on_output(output)
                for chunk in delta_gen.on_output(output):
                    await response.write(
                        f"data: {json.dumps(chunk)}\n\n".encode())
                if delta_gen.finish_reason is not None:
                    break
            if include_usage:
                usage_chunk = {"id": delta_gen.chunk_id,
                               "object": "chat.completion.chunk" if delta_gen.kind == "chat" else "text_completion",
                               "created": delta_gen.created, "model": model,
                               "choices": [], "usage": delta_gen.usage()}
                await response.write(f"data: {json.dumps(usage_chunk)}\n\n".encode())
            await response.write(b"data: [DONE]\n\n")
        except NoInstancesAvailable:
            await response.write(
                f"data: {json.dumps(_error_body(503, 'no workers available'))}\n\n".encode())
            await response.write(b"data: [DONE]\n\n")
        except AdmissionRefused as exc:
            # Mid-pipeline refusal after the stream headers went out:
            # surface in-band like every other post-prepare failure.
            get_recorder().finish(preprocessed.request_id, "shed")
            await response.write(
                f"data: {json.dumps(_error_body(503, str(exc), 'overloaded'))}\n\n".encode())
            await response.write(b"data: [DONE]\n\n")
        except DeadlineExceeded as exc:
            rt_metrics.DEADLINE_EXCEEDED.labels(component="frontend").inc()
            get_recorder().finish(preprocessed.request_id,
                                  "deadline_exceeded")
            await response.write(
                f"data: {json.dumps(_error_body(504, str(exc), 'deadline_exceeded'))}\n\n".encode())
            await response.write(b"data: [DONE]\n\n")
        except RemoteError as exc:
            # Emit an OpenAI-shaped error event then terminate the stream
            # cleanly so SDK clients see a parseable failure, not a dropped
            # chunked read.
            await response.write(
                f"data: {json.dumps(_error_body(502, str(exc), 'engine_error'))}\n\n".encode())
            await response.write(b"data: [DONE]\n\n")
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away: stop generating (cancellation propagates to
            # the worker through the request plane). Normal teardown — the
            # timeline closes as cancelled (no WARNING dump) and the
            # request is excluded from the goodput ratio.
            get_recorder().finish(preprocessed.request_id, "cancelled")
            disconnected = True
            log.info("client disconnected: %s", preprocessed.request_id)
            raise
        finally:
            rt_metrics.OUTPUT_TOKENS.labels(model=model).observe(
                delta_gen.completion_tokens)
            # finish_reason "error" is an in-band engine failure (the
            # worker streamed an error output), not a completion.
            status = ("ok" if delta_gen.finish_reason
                      not in (None, "error") else "error")
            # In-band SSE error terminations (deadline, engine error) must
            # export the server span as ERROR even though no exception
            # escapes the `with span:` (mirrors _anthropic_stream).
            span.end(ok=status == "ok" and not disconnected)
            if not disconnected:
                obs.finalize_from(delta_gen)
            self._count_request(model, status, start,
                                preprocessed=preprocessed,
                                delta_gen=delta_gen, kind=delta_gen.kind)
        await response.write_eof()
        return response

    # -- image / video generation (diffusion pools) ------------------------

    async def _diffusion_generate(self, model: str, body: dict,
                                  n_frames: int):
        """Call the model's diffusion pool; returns list of [frames, S, S,
        3] float arrays (one per image) or an error Response."""
        import numpy as np

        pool = self.manager.image_pools.get(model)
        if pool is None or not pool.instances:
            return web.json_response(_error_body(
                404, f"image model '{model}' not found", "model_not_found"),
                status=404)
        try:
            request = {
                "prompt": body.get("prompt", ""),
                "n": int(body.get("n", 1)),
                "steps": int(body.get("steps", 20)),
                "seed": int(body.get("seed", 0)),
                "frames": n_frames,
                # classifier-free guidance (production diffusion
                # sampling): scale > 1 steers away from negative_prompt
                # (or empty conditioning)
                "guidance_scale": float(body.get("guidance_scale", 1.0)),
                "negative_prompt": body.get("negative_prompt"),
            }
        except (TypeError, ValueError):
            return web.json_response(_error_body(
                400, "n/steps/seed/guidance_scale must be numbers"),
                status=400)
        if not request["prompt"]:
            return web.json_response(
                _error_body(400, "'prompt' is required"), status=400)
        images = []
        try:
            async for frame in pool.router.generate(request):
                if frame.get("error"):
                    return web.json_response(
                        _error_body(502, frame["error"], "engine_error"),
                        status=502)
                images.append(np.frombuffer(
                    frame["data"], np.float32).reshape(
                        tuple(frame["shape"])))
        except NoInstancesAvailable:
            return web.json_response(
                _error_body(503, "no diffusion workers", "overloaded"),
                status=503)
        return images

    async def _images(self, request: web.Request) -> web.Response:
        """OpenAI Images API (ref: openai.rs /v1/images/generations)."""
        try:
            body = await request.json()
        except (ValueError, UnicodeDecodeError):
            return web.json_response(_error_body(400, "invalid JSON body"),
                                     status=400)
        model = body.get("model", "")
        start = time.monotonic()
        status = "error"
        try:
            result = await self._diffusion_generate(model, body, n_frames=1)
            if isinstance(result, web.Response):
                return result
            from ..diffusion import to_png_b64 as _to_png_b64

            data = [{"b64_json": _to_png_b64(img[0])} for img in result]
            status = "ok"
            return web.json_response({"created": now_unix(), "data": data})
        finally:
            # count + audit every outcome (same invariant as the chat
            # routes: failures must not vanish from the trail)
            self._count_request(model, status, start, kind="images")

    async def _videos(self, request: web.Request) -> web.Response:
        """Video generation: N temporally-threaded frames returned as an
        animated GIF (ref: openai.rs /v1/videos route; the reference
        delegates to SGLang video diffusion)."""
        try:
            body = await request.json()
        except (ValueError, UnicodeDecodeError):
            return web.json_response(_error_body(400, "invalid JSON body"),
                                     status=400)
        model = body.get("model", "")
        try:
            fps = max(1, min(int(body.get("fps", 4)), 30))
            seconds = float(body.get("seconds", 1.0))
            n_frames = max(1, min(int(seconds * fps), 16))
        except (TypeError, ValueError, OverflowError):
            return web.json_response(_error_body(
                400, "fps/seconds must be finite numbers"), status=400)
        start = time.monotonic()
        status = "error"
        try:
            result = await self._diffusion_generate(model, body,
                                                    n_frames=n_frames)
            if isinstance(result, web.Response):
                return result
            from ..diffusion import to_gif_b64 as _to_gif_b64

            data = [{"b64_json": _to_gif_b64(img, fps=fps), "format": "gif",
                     "frames": int(img.shape[0])} for img in result]
            status = "ok"
            return web.json_response({"created": now_unix(), "data": data})
        finally:
            self._count_request(model, status, start, kind="videos")

    # -- embeddings --------------------------------------------------------

    def _embedding_inputs(self, raw, entry: ModelEntry) -> list[list[int]]:
        """Normalize OpenAI `input` (str | [str] | [int] | [[int]]) into
        token-id lists."""
        if isinstance(raw, str):
            return [entry.preprocessor.tokenizer.encode(raw)]
        if isinstance(raw, list) and raw:
            if all(isinstance(x, str) for x in raw):
                return [entry.preprocessor.tokenizer.encode(x) for x in raw]
            if all(isinstance(x, int) for x in raw):
                return [[int(x) for x in raw]]
            if all(isinstance(x, list) for x in raw):
                return [[int(t) for t in x] for x in raw]
        raise RequestError("'input' must be a string, list of strings, or "
                           "token array(s)")

    async def _embed_one(self, entry: ModelEntry, model: str,
                         token_ids: list[int]) -> list[float]:
        pre = PreprocessedRequest(
            request_id=new_request_id(),
            token_ids=token_ids,
            sampling=SamplingOptions(max_tokens=1, temperature=0.0),
            stop=StopConditions(),
            model=model,
            annotations={"embed": True},
        )
        async for out in entry.engine.generate(pre):
            if out.error:
                raise RemoteError(out.error)
            if out.embedding is not None:
                return out.embedding
            if out.finish_reason is not None:
                break
        raise RemoteError("worker returned no embedding")

    async def _embeddings(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
        except (ValueError, UnicodeDecodeError):
            return web.json_response(_error_body(400, "invalid JSON body"),
                                     status=400)
        model = body.get("model", "")
        entry, lora = self._lookup(model)
        if lora is not None:
            return web.json_response(_error_body(
                400, f"model '{model}' is a LoRA adapter; adapters are not "
                     "supported for embeddings"), status=400)
        self._check_busy(entry)
        # One id correlates the recorder entry with the audit record (the
        # join-by-request_id model every other endpoint follows).
        request_id = new_request_id()
        current_request_id.set(request_id)
        if self.recorder is not None:
            self.recorder.record_request(request_id, "embeddings", body)
        try:
            inputs = self._embedding_inputs(body.get("input"), entry)
            for toks in inputs:
                if len(toks) >= entry.card.context_length:
                    raise RequestError(
                        f"input of {len(toks)} tokens exceeds the model "
                        f"context length ({entry.card.context_length})")
        except RequestError as exc:
            return web.json_response(_error_body(400, str(exc)), status=400)
        encoding = body.get("encoding_format", "float")
        if encoding not in ("float", "base64"):
            return web.json_response(
                _error_body(400, "encoding_format must be float or base64"),
                status=400)
        total = sum(len(t) for t in inputs)
        start = time.monotonic()
        status = "error"
        try:
            try:
                vectors = await asyncio.gather(*[
                    self._embed_one(entry, model, toks) for toks in inputs
                ])
            except NoInstancesAvailable:
                return web.json_response(
                    _error_body(503, "no workers available", "overloaded"),
                    status=503)
            except RemoteError as exc:
                return web.json_response(
                    _error_body(502, str(exc), "engine_error"), status=502)
            data = []
            for i, vec in enumerate(vectors):
                if encoding == "base64":
                    import numpy as np

                    payload = base64.b64encode(
                        np.asarray(vec, np.float32).tobytes()).decode()
                else:
                    payload = vec
                data.append({"object": "embedding", "index": i,
                             "embedding": payload})
            status = "ok"
            return web.json_response({
                "object": "list",
                "data": data,
                "model": model,
                "usage": {"prompt_tokens": total, "total_tokens": total},
            })
        finally:
            self._count_request(model, status, start, kind="embeddings",
                                request_id=request_id, prompt_tokens=total)

    # -- Anthropic Messages API (ref: http/service/anthropic.rs) -----------

    @staticmethod
    def _messages_to_chat(body: dict) -> dict:
        """Lower an Anthropic Messages request onto the chat pipeline."""
        if not body.get("messages"):
            raise RequestError("'messages' is required")
        if not body.get("max_tokens"):
            raise RequestError("'max_tokens' is required")
        messages = []
        system = body.get("system")
        if system:
            if isinstance(system, list):  # content-block form
                system = "".join(b.get("text", "") for b in system
                                 if isinstance(b, dict))
            messages.append({"role": "system", "content": system})
        for msg in body["messages"]:
            content = msg.get("content")
            if isinstance(content, list):
                content = "".join(b.get("text", "") for b in content
                                  if isinstance(b, dict)
                                  and b.get("type") == "text")
            messages.append({"role": msg.get("role", "user"),
                             "content": content or ""})
        chat = {
            "model": body.get("model", ""),
            "messages": messages,
            "max_tokens": body["max_tokens"],
            "temperature": body.get("temperature", 1.0),
            "top_p": body.get("top_p", 1.0),
            "top_k": body.get("top_k", 0),
            "stop": body.get("stop_sequences"),
        }
        # QoS fields ride every completion-shaped endpoint
        # (docs/multi-tenancy.md); the preprocessor validates the class.
        if body.get("priority"):
            chat["priority"] = body["priority"]
        if body.get("tenant"):
            chat["tenant"] = body["tenant"]
        return chat

    @staticmethod
    def _anthropic_stop(delta_gen: DeltaGenerator) -> tuple[str, Optional[str]]:
        """(stop_reason, stop_sequence) in Anthropic terms."""
        if delta_gen.stop_sequence_hit is not None:
            return "stop_sequence", delta_gen.stop_sequence_hit
        reason = {"length": "max_tokens"}.get(
            delta_gen.finish_reason or "stop", "end_turn")
        return reason, None

    async def _anthropic_messages(self, request: web.Request) -> web.StreamResponse:
        arrival = time.time()
        try:
            body = await request.json()
        except (ValueError, UnicodeDecodeError):
            return web.json_response(_error_body(400, "invalid JSON body"),
                                     status=400)
        model = body.get("model", "")
        entry, lora = self._lookup(model)
        self._check_busy(entry)
        deadline = self._admit_deadline(request, entry)
        self._check_queue_admission(entry, deadline,
                                    tenant=self._tenant_of(request, body))
        clean_body, sid, anchors_raw = self._session_prepare(request, body)
        clean_body = self._fold_qos_headers(request, clean_body)
        try:
            chat_body = self._messages_to_chat(clean_body)
            preprocessed = entry.preprocessor.preprocess_chat(chat_body)
        except RequestError as exc:
            return web.json_response(_error_body(400, str(exc)), status=400)
        self._check_tenant_quota(entry, preprocessed)
        preprocessed.lora_name = lora
        preprocessed.deadline = deadline
        if self.recorder is not None:
            self.recorder.record_request(
                preprocessed.request_id, "messages", body)
        current_request_id.set(preprocessed.request_id)
        span = get_tracer().start_span(
            "http.messages", parent=request.headers.get("traceparent"),
            kind=2, **{"request.id": preprocessed.request_id,
                       "model": model,
                       "input.tokens": len(preprocessed.token_ids)})
        self._open_http_trace(request, preprocessed, span, received=arrival)
        # Anthropic anchor indices are against body["messages"]; the
        # lowered chat list may prepend a system message — remap (-1 =
        # marked system block -> chat index 0).
        chat_msgs = chat_body.get("messages") or []
        offset = 1 if (chat_msgs and chat_msgs[0].get("role") == "system") \
            else 0
        self._session_register(
            entry, preprocessed, chat_msgs, sid,
            [(i if i < 0 else i + offset, ttl) for i, ttl in anchors_raw])
        return await self._finish_guard(
            preprocessed.request_id,
            self._messages_traced(
                request, entry, preprocessed, span, body, model),
            span=span)

    async def _messages_traced(
        self, request: web.Request, entry: ModelEntry,
        preprocessed: PreprocessedRequest, span, body: dict, model: str,
    ) -> web.StreamResponse:
        delta_gen = DeltaGenerator(entry.preprocessor, preprocessed,
                                   kind="chat")
        msg_id = f"msg_{uuid.uuid4().hex[:24]}"
        if bool(body.get("stream", False)):
            return await self._anthropic_stream(request, entry, preprocessed,
                                                delta_gen, msg_id, span)
        start = time.monotonic()
        status = "error"
        try:
            err = await self._consume(entry, preprocessed, delta_gen,
                                      observe_latency=True)
            if err is not None:
                return err
            status = "ok"
        finally:
            span.end(ok=status == "ok")
            self._count_request(model, status, start,
                                preprocessed=preprocessed,
                                delta_gen=delta_gen, kind="messages")
        stop_reason, stop_sequence = self._anthropic_stop(delta_gen)
        return web.json_response({
            "id": msg_id,
            "type": "message",
            "role": "assistant",
            "model": model,
            "content": [{"type": "text", "text": delta_gen.full_text}],
            "stop_reason": stop_reason,
            "stop_sequence": stop_sequence,
            "usage": {
                "input_tokens": len(preprocessed.token_ids),
                "output_tokens": delta_gen.completion_tokens,
            },
        })

    async def _anthropic_stream(
        self, request: web.Request, entry: ModelEntry,
        preprocessed: PreprocessedRequest, delta_gen: DeltaGenerator,
        msg_id: str, span,
    ) -> web.StreamResponse:
        response = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache",
                     "X-Request-Id": preprocessed.request_id},
        )
        await response.prepare(request)

        async def emit(event: str, payload: dict) -> None:
            await response.write(
                f"event: {event}\ndata: {json.dumps(payload)}\n\n".encode())

        await emit("message_start", {
            "type": "message_start",
            "message": {"id": msg_id, "type": "message", "role": "assistant",
                        "model": preprocessed.model, "content": [],
                        "stop_reason": None, "stop_sequence": None,
                        "usage": {"input_tokens": len(preprocessed.token_ids),
                                  "output_tokens": 0}},
        })
        await emit("content_block_start", {
            "type": "content_block_start", "index": 0,
            "content_block": {"type": "text", "text": ""},
        })
        start = time.monotonic()
        obs = _SloObserver(preprocessed, self.slo_ttft_ms, self.slo_itl_ms,
                           wait_estimator=entry.wait_estimator)
        errored = False
        disconnected = False
        try:
            async for output in self._generate(entry, preprocessed):
                obs.on_output(output)
                if output.error:
                    errored = True
                    await emit("error", {"type": "error",
                                         "error": {"type": "api_error",
                                                   "message": output.error}})
                    break
                for chunk in delta_gen.on_output(output):
                    text = chunk["choices"][0]["delta"].get("content")
                    if text:
                        await emit("content_block_delta", {
                            "type": "content_block_delta", "index": 0,
                            "delta": {"type": "text_delta", "text": text},
                        })
                if delta_gen.finish_reason is not None:
                    break
            if not errored:
                stop_reason, stop_sequence = self._anthropic_stop(delta_gen)
                await emit("content_block_stop",
                           {"type": "content_block_stop", "index": 0})
                await emit("message_delta", {
                    "type": "message_delta",
                    "delta": {"stop_reason": stop_reason,
                              "stop_sequence": stop_sequence},
                    "usage": {"output_tokens": delta_gen.completion_tokens},
                })
                await emit("message_stop", {"type": "message_stop"})
        except (NoInstancesAvailable, AdmissionRefused, RemoteError) as exc:
            errored = True
            if isinstance(exc, AdmissionRefused):
                # Deliberate early shed, not a failure: keep its
                # timeline out of the error auto-dump storm.
                get_recorder().finish(preprocessed.request_id, "shed")
            await emit("error", {"type": "error",
                                 "error": {"type": "api_error",
                                           "message": str(exc)}})
        except DeadlineExceeded as exc:
            # Same classification as the chat stream: counted, recorded
            # as deadline_exceeded (not a bare error), surfaced as a
            # parseable error event instead of a dropped chunked read.
            errored = True
            rt_metrics.DEADLINE_EXCEEDED.labels(component="frontend").inc()
            get_recorder().finish(preprocessed.request_id,
                                  "deadline_exceeded")
            await emit("error", {"type": "error",
                                 "error": {"type": "timeout_error",
                                           "message": str(exc)}})
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away: normal teardown, excluded from goodput.
            get_recorder().finish(preprocessed.request_id, "cancelled")
            disconnected = True
            raise
        finally:
            ok = delta_gen.finish_reason is not None and not errored
            span.end(ok=ok and not disconnected)
            if not disconnected:
                obs.finalize_from(delta_gen)
            self._count_request(preprocessed.model,
                                "ok" if ok else "error", start,
                                preprocessed=preprocessed,
                                delta_gen=delta_gen, kind="messages")
        await response.write_eof()
        return response

    # -- OpenAI Responses API ----------------------------------------------

    @staticmethod
    def _responses_to_chat(body: dict) -> dict:
        """Lower a Responses API request onto the chat pipeline."""
        raw = body.get("input")
        if raw is None:
            raise RequestError("'input' is required")
        messages = []
        instructions = body.get("instructions")
        if instructions:
            messages.append({"role": "system", "content": instructions})
        if isinstance(raw, str):
            messages.append({"role": "user", "content": raw})
        elif isinstance(raw, list):
            for item in raw:
                if not isinstance(item, dict):
                    raise RequestError("input items must be objects")
                content = item.get("content")
                if isinstance(content, list):
                    content = "".join(
                        b.get("text", "") for b in content
                        if isinstance(b, dict)
                        and b.get("type") in ("input_text", "output_text",
                                              "text"))
                messages.append({"role": item.get("role", "user"),
                                 "content": content or ""})
        else:
            raise RequestError("'input' must be a string or message list")
        chat = {
            "model": body.get("model", ""),
            "messages": messages,
            "max_tokens": body.get("max_output_tokens"),
            "temperature": body.get("temperature", 1.0),
            "top_p": body.get("top_p", 1.0),
        }
        # QoS fields ride every completion-shaped endpoint.
        if body.get("priority"):
            chat["priority"] = body["priority"]
        if body.get("tenant"):
            chat["tenant"] = body["tenant"]
        return chat

    def _responses_body(self, resp_id: str, model: str,
                        delta_gen: DeltaGenerator, status: str) -> dict:
        return {
            "id": resp_id,
            "object": "response",
            "created_at": now_unix(),
            "status": status,
            "model": model,
            "output": [{
                "type": "message",
                "id": f"msg_{uuid.uuid4().hex[:24]}",
                "status": status,
                "role": "assistant",
                "content": [{"type": "output_text",
                             "text": delta_gen.full_text,
                             "annotations": []}],
            }],
            "usage": {
                "input_tokens": len(delta_gen.request.token_ids),
                "output_tokens": delta_gen.completion_tokens,
                "total_tokens": (len(delta_gen.request.token_ids)
                                 + delta_gen.completion_tokens),
            },
        }

    async def _responses(self, request: web.Request) -> web.StreamResponse:
        arrival = time.time()
        try:
            body = await request.json()
        except (ValueError, UnicodeDecodeError):
            return web.json_response(_error_body(400, "invalid JSON body"),
                                     status=400)
        model = body.get("model", "")
        entry, lora = self._lookup(model)
        self._check_busy(entry)
        deadline = self._admit_deadline(request, entry)
        self._check_queue_admission(entry, deadline,
                                    tenant=self._tenant_of(request, body))
        body = self._fold_qos_headers(request, body)
        try:
            chat_body = self._responses_to_chat(body)
            preprocessed = entry.preprocessor.preprocess_chat(chat_body)
        except RequestError as exc:
            return web.json_response(_error_body(400, str(exc)), status=400)
        self._check_tenant_quota(entry, preprocessed)
        preprocessed.lora_name = lora
        preprocessed.deadline = deadline
        if self.recorder is not None:
            self.recorder.record_request(
                preprocessed.request_id, "responses", body)
        current_request_id.set(preprocessed.request_id)
        span = get_tracer().start_span(
            "http.responses", parent=request.headers.get("traceparent"),
            kind=2, **{"request.id": preprocessed.request_id,
                       "model": model,
                       "input.tokens": len(preprocessed.token_ids)})
        self._open_http_trace(request, preprocessed, span, received=arrival)
        return await self._finish_guard(
            preprocessed.request_id,
            self._responses_traced(
                request, entry, preprocessed, span, body, model),
            span=span)

    async def _responses_traced(
        self, request: web.Request, entry: ModelEntry,
        preprocessed: PreprocessedRequest, span, body: dict, model: str,
    ) -> web.StreamResponse:
        delta_gen = DeltaGenerator(entry.preprocessor, preprocessed,
                                   kind="chat")
        resp_id = f"resp_{uuid.uuid4().hex[:24]}"
        if bool(body.get("stream", False)):
            return await self._responses_stream(request, entry, preprocessed,
                                                delta_gen, resp_id, span)
        start = time.monotonic()
        status = "error"
        try:
            err = await self._consume(entry, preprocessed, delta_gen,
                                      observe_latency=True)
            if err is not None:
                return err
            status = "ok"
        finally:
            span.end(ok=status == "ok")
            self._count_request(model, status, start,
                                preprocessed=preprocessed,
                                delta_gen=delta_gen, kind="responses")
        return web.json_response(
            self._responses_body(resp_id, model, delta_gen, "completed"))

    async def _responses_stream(
        self, request: web.Request, entry: ModelEntry,
        preprocessed: PreprocessedRequest, delta_gen: DeltaGenerator,
        resp_id: str, span,
    ) -> web.StreamResponse:
        response = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache",
                     "X-Request-Id": preprocessed.request_id},
        )
        await response.prepare(request)

        async def emit(event: str, payload: dict) -> None:
            await response.write(
                f"event: {event}\ndata: {json.dumps(payload)}\n\n".encode())

        await emit("response.created", {
            "type": "response.created",
            "response": self._responses_body(resp_id, preprocessed.model,
                                             delta_gen, "in_progress"),
        })
        start = time.monotonic()
        obs = _SloObserver(preprocessed, self.slo_ttft_ms, self.slo_itl_ms,
                           wait_estimator=entry.wait_estimator)
        errored = False
        disconnected = False
        try:
            async for output in self._generate(entry, preprocessed):
                obs.on_output(output)
                if output.error:
                    errored = True
                    await emit("error", {"type": "error",
                                         "message": output.error})
                    break
                for chunk in delta_gen.on_output(output):
                    text = chunk["choices"][0]["delta"].get("content")
                    if text:
                        await emit("response.output_text.delta", {
                            "type": "response.output_text.delta",
                            "delta": text,
                        })
                if delta_gen.finish_reason is not None:
                    break
            if not errored:
                await emit("response.output_text.done", {
                    "type": "response.output_text.done",
                    "text": delta_gen.full_text,
                })
                await emit("response.completed", {
                    "type": "response.completed",
                    "response": self._responses_body(
                        resp_id, preprocessed.model, delta_gen, "completed"),
                })
        except (NoInstancesAvailable, AdmissionRefused, RemoteError) as exc:
            errored = True
            if isinstance(exc, AdmissionRefused):
                # Deliberate early shed, not a failure: keep its
                # timeline out of the error auto-dump storm.
                get_recorder().finish(preprocessed.request_id, "shed")
            await emit("error", {"type": "error", "message": str(exc)})
        except DeadlineExceeded as exc:
            # Same classification as the chat stream (see _stream_response).
            errored = True
            rt_metrics.DEADLINE_EXCEEDED.labels(component="frontend").inc()
            get_recorder().finish(preprocessed.request_id,
                                  "deadline_exceeded")
            await emit("error", {"type": "error",
                                 "message": str(exc),
                                 "code": "deadline_exceeded"})
        except (ConnectionResetError, asyncio.CancelledError):
            # Client went away: normal teardown, excluded from goodput.
            get_recorder().finish(preprocessed.request_id, "cancelled")
            disconnected = True
            raise
        finally:
            ok = delta_gen.finish_reason is not None and not errored
            span.end(ok=ok and not disconnected)
            if not disconnected:
                obs.finalize_from(delta_gen)
            self._count_request(preprocessed.model,
                                "ok" if ok else "error", start,
                                preprocessed=preprocessed,
                                delta_gen=delta_gen, kind="responses")
        await response.write_eof()
        return response

    # -- lifecycle ---------------------------------------------------------

    # -- admin + docs routes (ref: busy_threshold.rs, clear_kv_blocks.rs,
    # service_v2.rs /openapi.json + /docs) --------------------------------

    async def _busy_threshold_list(self, _request: web.Request) -> web.Response:
        return web.json_response({"thresholds": [
            {"model": m, "active_decode_blocks_threshold": v}
            for m, v in sorted(self.busy_thresholds.items())
        ]})

    async def _busy_threshold_post(self, request: web.Request) -> web.Response:
        """Get or set a model's busy threshold: body with a threshold
        sets it; body with only the model name reads it back (the
        reference's get-or-set POST contract, busy_threshold.rs)."""
        try:
            body = await request.json()
        except Exception:
            return web.json_response(
                _error_body(400, "invalid JSON body"), status=400)
        model = body.get("model")
        if not isinstance(model, str) or not model:
            return web.json_response(
                _error_body(400, "'model' is required"), status=400)
        entry, _ = self.manager.resolve(model)
        if entry is None:
            return web.json_response(
                _error_body(404, f"model '{model}' not found",
                            "model_not_found"), status=404)
        name = entry.card.name
        value = body.get("active_decode_blocks_threshold",
                         body.get("busy_threshold"))
        if value is not None:
            try:
                value = float(value)
            except (TypeError, ValueError):
                return web.json_response(_error_body(
                    400, "active_decode_blocks_threshold must be a "
                    "number in [0, 1]"), status=400)
            if not 0.0 <= value <= 1.0:
                return web.json_response(_error_body(
                    400, "active_decode_blocks_threshold must be in "
                    "[0, 1]"), status=400)
            self.busy_thresholds[name] = value
        current = self.busy_thresholds.get(name, self.busy_threshold)
        return web.json_response(
            {"model": name, "active_decode_blocks_threshold": current})

    async def _clear_kv_blocks(self, _request: web.Request) -> web.Response:
        """Fan out to every worker group's clear_kv_blocks endpoint and
        report per-worker outcomes (ref: clear_kv_blocks.rs)."""
        entries = self.manager.entries()
        if not entries:
            return web.json_response(
                {"message": "No active worker groups found"})
        if self.runtime is None:
            return web.json_response(
                {"message": "Failed to create distributed runtime"})
        cleared, failed = [], []
        seen: set[tuple[str, str]] = set()
        for entry in entries:
            card = entry.card
            key = (card.namespace, card.component)
            if key in seen:  # chat+completions share a worker group
                continue
            seen.add(key)
            endpoint = f"{card.namespace}/{card.component}/clear_kv_blocks"
            client = None
            try:
                client = (
                    self.runtime.namespace(card.namespace)
                    .component(card.component)
                    .endpoint("clear_kv_blocks")
                    .client()
                )
                await client.start()
                instance_ids = list(client.instance_ids()) or [None]
                for iid in instance_ids:
                    rec = {"name": card.name, "endpoint": endpoint,
                           "instance": iid}
                    try:
                        if iid is None:
                            raise RuntimeError("no live instances")
                        async for resp in client.direct({}, iid):
                            rec["response"] = resp
                            break
                        rec["status"] = "cleared"
                        cleared.append(rec)
                    except Exception as exc:  # noqa: BLE001 — report
                        rec["status"] = "failed"
                        rec["error"] = str(exc)
                        failed.append(rec)
            except Exception as exc:  # noqa: BLE001 — report per group
                failed.append({"name": card.name, "endpoint": endpoint,
                               "status": "failed", "error": str(exc)})
            finally:
                # per-request client: close its discovery watcher/task
                # or every POST leaks one for the frontend's lifetime
                if client is not None:
                    try:
                        await client.close()
                    except Exception:  # noqa: BLE001 — best-effort
                        log.exception("clear_kv client close failed")
        return web.json_response(
            {"cleared_workers": cleared, "failed_workers": failed})

    # (method, path, summary) — drives both the aiohttp route table and
    # the generated OpenAPI document (ref: RouteDoc in service_v2.rs).
    _ROUTE_DOCS = (
        ("post", "/v1/chat/completions",
         "OpenAI chat completions (SSE streaming + aggregate)"),
        ("post", "/v1/completions", "OpenAI text completions"),
        ("post", "/v1/embeddings", "OpenAI embeddings"),
        ("post", "/v1/messages", "Anthropic messages"),
        ("post", "/v1/responses", "OpenAI responses"),
        ("post", "/v1/images/generations", "Image generation (diffusion)"),
        ("post", "/v1/videos", "Video generation (diffusion)"),
        ("get", "/v1/models", "List served models, adapters, and pools"),
        ("get", "/health", "Service health + served model list"),
        ("get", "/live", "Liveness probe"),
        ("get", "/metrics",
         "Prometheus metrics (OpenMetrics + exemplars via Accept)"),
        ("get", "/debug/requests",
         "Flight recorder: inflight + recent request timelines"),
        ("get", "/debug/profile",
         "On-demand jax.profiler capture (?duration_ms=); returns the "
         "trace artifact path"),
        ("get", "/busy_threshold", "List per-model busy thresholds"),
        ("post", "/busy_threshold",
         "Get or set a model's busy threshold (load shedding)"),
        ("post", "/clear_kv_blocks",
         "Clear every worker's KV prefix cache"),
        ("get", "/openapi.json", "This OpenAPI document"),
        ("get", "/docs", "Human-readable API index"),
    )

    def _route_docs(self):
        """_ROUTE_DOCS minus routes not actually registered (the opt-in
        /debug/* endpoints), so /openapi.json and /docs never advertise
        an endpoint that 404s."""
        if env("DYNT_DEBUG_ENDPOINTS"):
            return self._ROUTE_DOCS
        return tuple(r for r in self._ROUTE_DOCS
                     if not r[1].startswith("/debug/"))

    async def _openapi(self, _request: web.Request) -> web.Response:
        paths: dict[str, dict] = {}
        for method, path, summary in self._route_docs():
            paths.setdefault(path, {})[method] = {
                "summary": summary,
                "responses": {"200": {"description": "OK"}},
            }
        return web.json_response({
            "openapi": "3.0.3",
            "info": {"title": "dynamo_tpu frontend",
                     "version": "1.0.0"},
            "paths": paths,
        })

    async def _docs(self, _request: web.Request) -> web.Response:
        # Self-contained (zero-CDN) index rendered from _ROUTE_DOCS; the
        # machine-readable spec lives at /openapi.json.
        rows = "".join(
            f"<tr><td><code>{m.upper()}</code></td>"
            f"<td><code>{p}</code></td><td>{s}</td></tr>"
            for m, p, s in self._route_docs())
        html = (
            "<!doctype html><html><head><title>dynamo_tpu API</title>"
            "<style>body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}td,th{border:1px solid #ccc;"
            "padding:4px 10px;text-align:left}</style></head><body>"
            "<h1>dynamo_tpu frontend API</h1>"
            "<p>Machine-readable spec: <a href='/openapi.json'>"
            "/openapi.json</a></p>"
            f"<table><tr><th>Method</th><th>Path</th><th>Summary</th></tr>"
            f"{rows}</table></body></html>")
        return web.Response(text=html, content_type="text/html")

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/v1/chat/completions", self._chat)
        app.router.add_post("/v1/completions", self._completions)
        app.router.add_post("/v1/embeddings", self._embeddings)
        app.router.add_post("/v1/messages", self._anthropic_messages)
        app.router.add_post("/v1/responses", self._responses)
        app.router.add_post("/v1/images/generations", self._images)
        app.router.add_post("/v1/videos", self._videos)
        app.router.add_get("/v1/models", self._models)
        app.router.add_get("/health", self._health)
        app.router.add_get("/live", self._health)
        app.router.add_get("/metrics", self._metrics)
        if env("DYNT_DEBUG_ENDPOINTS"):
            # Tenant-facing port: the flight recorder exposes every
            # client's request timelines and a profile capture burns
            # serving-process time, so both are opt-in here (the
            # internal status server always serves them).
            app.router.add_get("/debug/requests", self._debug_requests)
            app.router.add_get("/debug/profile", self._debug_profile)
        app.router.add_get("/busy_threshold", self._busy_threshold_list)
        app.router.add_post("/busy_threshold", self._busy_threshold_post)
        app.router.add_post("/clear_kv_blocks", self._clear_kv_blocks)
        app.router.add_get("/openapi.json", self._openapi)
        app.router.add_get("/docs", self._docs)
        return app

    async def start(self) -> None:
        self._runner = web.AppRunner(self.build_app(), access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]  # type: ignore[union-attr]
        log.info("OpenAI frontend listening on %s:%d", self.host, self.port)

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
