"""PrefillRouter: orchestrates disaggregated prefill/decode serving.

Ref: lib/llm/src/kv_router/prefill_router/mod.rs:43 + §3.4 —
  * inactive while no prefill-pool workers exist: requests pass straight
    through to the decode engine (aggregated fallback)
  * active: clone the request with max_tokens=1 + `prefill_only`, send it to
    a prefill worker, take the returned kv_transfer_params, inject them as
    `disaggregated_params` into the decode request, and stream from decode
    (the decode worker pulls the KV blocks before admitting — kv_transfer.py)

Activation is dynamic (runtime-reconfigurable xPyD): the ModelWatcher
maintains a PrefillPool per model as prefill cards come and go; this engine
checks the pool on every request.
"""

from __future__ import annotations

import dataclasses
from typing import AsyncIterator, Callable, Optional

from ..runtime.admission import (
    QueueWaitEstimator,
    check_admission,
    check_tenant_admission,
    get_tenant_ledger,
)
from ..runtime.logging import get_logger
from ..runtime.otel import get_tracer
from ..runtime.push_router import NoInstancesAvailable, PushRouter
from ..runtime.resilience import DeadlineExceeded
from .engine import TokenEngine
from .protocols import EngineOutput, PreprocessedRequest, SamplingOptions

log = get_logger("llm.prefill_router")


def _prefill_estimator() -> QueueWaitEstimator:
    return QueueWaitEstimator(pool="prefill")


@dataclasses.dataclass
class PrefillPool:
    """A model's prefill workers (one endpoint subject + live instances)."""

    router: PushRouter
    instances: set[int] = dataclasses.field(default_factory=set)
    # Deadline-aware admission: queue-wait estimate for the prefill pool —
    # depth from the pool workers' waiting_requests (LoadMetrics, fed by
    # the ModelWatcher), drain rate from completed prefill legs observed
    # right here. Isolated from the decode pool's estimator so a drowning
    # prefill tier cannot poison decode admission (and vice versa).
    wait_estimator: QueueWaitEstimator = dataclasses.field(
        default_factory=_prefill_estimator)

    def active(self) -> bool:
        return bool(self.instances)


class PrefillRouterEngine(TokenEngine):
    def __init__(
        self,
        inner: TokenEngine,
        pool_lookup: Callable[[], Optional[PrefillPool]],
    ) -> None:
        self.inner = inner
        self.pool_lookup = pool_lookup
        # Background drains of still-running streaming prefill legs
        # (docs/disaggregation.md): the decode leg dispatches as soon as
        # the FIRST chunk's transfer params arrive, but the prefill
        # stream must keep being consumed (closing it would cancel the
        # prefill worker's request mid-prompt).
        self._drains: set = set()

    def _drain_prefill_leg(self, agen, span, request_id: str) -> None:
        """Consume the rest of a streaming prefill leg in the background.
        The decode side is already pulling; an error here needs no
        handling — the pull stream fails and the decode worker recomputes
        (the same fallback every transfer failure takes)."""
        import asyncio

        async def _drain() -> None:
            ok = False
            try:
                async for item in agen:
                    out = EngineOutput.from_wire(item)
                    if out.error:
                        log.warning("streaming prefill leg error for %s: %s",
                                    request_id, out.error)
                        return
                    if out.finish_reason is not None:
                        ok = True
                        return
            except Exception as exc:  # noqa: BLE001 — decode side
                # recomputes via the failed pull; nothing to surface here
                log.warning("streaming prefill leg failed for %s (%r)",
                            request_id, exc)
            finally:
                span.end(ok=ok)

        task = asyncio.create_task(_drain())
        self._drains.add(task)
        task.add_done_callback(self._drains.discard)

    async def _run_prefill(
        self, pool: PrefillPool, request: PreprocessedRequest
    ) -> Optional[dict]:
        """Send the prompt to a prefill worker; returns kv_transfer_params
        or None (caller falls back to aggregated)."""
        # The prefill leg gets its own span: the prefill worker's server
        # span parents under it, so the trace separates prefill execution
        # from the decode leg that follows.
        span = get_tracer().start_span(
            "prefill.remote",
            parent=request.annotations.get("traceparent"),
            **{"request.id": request.request_id,
               "input.tokens": len(request.token_ids)})
        leg_tp = span.traceparent or request.annotations.get("traceparent")
        annotations = {**request.annotations, "prefill_only": True}
        if leg_tp:
            annotations["traceparent"] = leg_tp
        prefill_request = dataclasses.replace(
            request,
            sampling=dataclasses.replace(request.sampling, max_tokens=1),
            annotations=annotations,
        )
        # Gateway EPP header contract (ref: prefill_router/mod.rs:117-120
        # x-prefill-instance-id): an external picker pins the prefill leg.
        target = None
        raw = request.annotations.get("prefill_instance")
        if raw:
            try:
                target = int(str(raw), 16)
            except ValueError:
                log.warning("bad prefill_instance annotation %r", raw)
        streaming = False
        # The prefill leg draws on the request's REMAINING budget
        # (router re-encodes it per attempt) — a slow prefill pool
        # can no longer eat more than the end-to-end deadline.
        agen = pool.router.generate(prefill_request.to_wire(),
                                    instance_id=target,
                                    deadline=request.deadline,
                                    traceparent=leg_tp)
        try:
            async for item in agen:
                out = EngineOutput.from_wire(item)
                if out.error:
                    log.warning("prefill worker error for %s: %s",
                                request.request_id, out.error)
                    return None
                if out.kv_transfer_params is not None:
                    # A completed leg = one unit drained from the prefill
                    # queue — the drain-rate signal the pool's admission
                    # estimator divides the backlog by.
                    pool.wait_estimator.observe_drained(1)
                    params = out.kv_transfer_params
                    if params.get("streaming") \
                            and "first_token" not in params:
                        # Chunked handoff (docs/disaggregation.md): the
                        # prefill worker streamed transfer params after
                        # its FIRST chunk. Dispatch the decode leg NOW —
                        # it starts pulling parked chunks while later
                        # chunks compute — and keep consuming the prefill
                        # stream in the background (closing it would
                        # cancel the prefill request mid-prompt).
                        streaming = True
                        self._drain_prefill_leg(agen, span,
                                                request.request_id)
                        return params
                    span.end(ok=True)
                    return params
        except DeadlineExceeded:
            # No budget left: the decode leg could not finish either —
            # surface the overrun instead of burning a recompute.
            span.add_event("deadline_exceeded")
            raise
        except Exception as exc:  # noqa: BLE001 — any prefill-leg failure
            # (incl. NoInstancesAvailable) degrades to aggregated serving
            log.warning("prefill leg failed for %s (%r); aggregated fallback",
                        request.request_id, exc)
            return None
        finally:
            # Fallback paths (error output, transport failure, no params)
            # close the span ok=False; the success return above already
            # ended it ok=True (first end wins). A streaming leg keeps
            # its span open — the background drain closes it.
            if not streaming:
                span.end(ok=False)
        return None

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[EngineOutput]:
        pool = self.pool_lookup()
        if (request.disaggregated_params or {}).get("handoff") is not None:
            # Graceful-drain KV handoff replay (engine/drain.py): the
            # request already carries its pull route + resume state —
            # the destination pulls the SOURCE's computed pages and
            # continues the stream. A prefill leg here would recompute
            # KV the handoff exists to preserve (and clobber the params).
            pool = None
        elif request.annotations.get("embed"):
            # Embeddings have no KV to hand off — a prefill leg would just
            # compute the same trunk twice.
            pool = None
        if pool is None or not pool.active():
            async for out in self.inner.generate(request):
                yield out
            return
        # Deadline-aware admission for the prefill tier: refuse (503 via
        # AdmissionRefused at the frontend) BEFORE dispatching the leg —
        # a budget that cannot survive the prefill queue would burn a
        # full prompt pass for a client that has already timed out. The
        # wait is the backlog AHEAD of this leg; an idle pool admits.
        # An over-share tenant is quota-refused first when the prefill
        # pool is backlogged (contention is prefill-pool-local here).
        # tokens=0: the entry edge already deposited this request's
        # cost — re-adding it would double-count it against its share.
        check_tenant_admission(
            get_tenant_ledger(), request.tenant, 0,
            contended=pool.wait_estimator.depth() > 0)
        check_admission(pool.wait_estimator, request.deadline,
                        tenant=request.tenant)
        params = await self._run_prefill(pool, request)
        if params is not None:
            request = dataclasses.replace(
                request, disaggregated_params=params
            )
        async for out in self.inner.generate(request):
            yield out
