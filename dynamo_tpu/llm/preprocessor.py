"""OpenAI preprocessor: request lowering and response delta generation.

Forward edge: apply chat template -> tokenize -> PreprocessedRequest with
sampling + stop conditions (ref: lib/llm/src/preprocessor.rs:147,225).
Backward edge: incremental detokenization + OpenAI SSE delta construction
with stop-string jailing — text that might be a prefix of a stop string is
held until disambiguated (ref: backend.rs detokenizer + http delta path,
chat_completions/jail.rs).
"""

from __future__ import annotations

import dataclasses
from typing import Any, AsyncIterator, Optional

import jinja2

from .model_card import ModelDeploymentCard
from .protocols import (
    EngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
    new_request_id,
    now_unix,
    openai_chunk_id,
)
from .tokenizer import IncrementalDetokenizer, Tokenizer, load_tokenizer

# ChatML — the de-facto default template when a model ships none.
DEFAULT_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "<|im_start|>{{ message['role'] }}\n{{ message['content'] }}<|im_end|>\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|im_start|>assistant\n{% endif %}"
)


class RequestError(ValueError):
    """Invalid user request -> HTTP 400."""


class OpenAIPreprocessor:
    def __init__(self, card: ModelDeploymentCard,
                 tokenizer: Optional[Tokenizer] = None) -> None:
        self.card = card
        self.tokenizer = tokenizer or load_tokenizer(card.tokenizer)
        template = card.chat_template or self.tokenizer.chat_template \
            or DEFAULT_CHAT_TEMPLATE
        self._template = jinja2.Environment().from_string(template)

    # -- forward: OpenAI request -> PreprocessedRequest --------------------

    def render_chat(self, messages: list[dict]) -> str:
        for msg in messages:
            if not isinstance(msg, dict) or "role" not in msg:
                raise RequestError("each message needs a 'role'")
            content = msg.get("content")
            if isinstance(content, list):
                # Multimodal content parts: concatenate text parts (image
                # parts are resolved by the multimodal path, not here).
                msg["content"] = "".join(
                    part.get("text", "") for part in content
                    if isinstance(part, dict) and part.get("type") == "text"
                )
        return self._template.render(messages=messages, add_generation_prompt=True)

    def preprocess_chat(self, request: dict) -> PreprocessedRequest:
        from .validate import validate_request

        validate_request(request, "chat")
        messages = request.get("messages")
        if not messages:
            raise RequestError("'messages' is required and must be non-empty")
        if any(isinstance(m.get("content"), list)
               and any(isinstance(p, dict) and p.get("type") == "image_url"
                       for p in m["content"])
               for m in messages if isinstance(m, dict)):
            return self._preprocess_multimodal(list(messages), request)
        prompt = self.render_chat(list(messages))
        return self._build(prompt, request)

    def _preprocess_multimodal(self, messages: list[dict],
                               request: dict) -> PreprocessedRequest:
        """Image content parts -> placeholder tokens + media identity (ref:
        preprocessor/media.rs resolving multimodal media before the
        engine). The card must advertise multimodal support (worker
        runtime_config) with the placeholder id + rows-per-image."""
        from .media import IMAGE_MARKER, extract_image_parts, media_hash

        mm = self.card.runtime_config.get("multimodal")
        if not mm:
            raise RequestError(
                f"model '{self.card.name}' does not accept image input")
        image_token_id = int(mm["image_token_id"])
        # extract_image_parts inserts the NUL-delimited marker at image
        # positions and strips NULs from user text, so a literal "<image>"
        # in content cannot forge a slot.
        flat_messages, urls = extract_image_parts(messages)
        prompt = self.render_chat(flat_messages)
        pieces = prompt.split(IMAGE_MARKER)
        if len(pieces) - 1 != len(urls):
            raise RequestError("image marker/url count mismatch")
        token_ids: list[int] = []
        for i, piece in enumerate(pieces):
            if piece:
                # _encode_text drops placeholder ids produced from text —
                # they must only mark image positions.
                token_ids.extend(self._encode_text(piece))
            if i < len(urls):
                token_ids.extend(
                    [image_token_id] * int(mm["n_image_tokens"]))
        pre = self._build_from_tokens(token_ids, request)
        pre.annotations["media_urls"] = urls
        pre.media_hashes = [media_hash(u) for u in urls]
        return pre

    def preprocess_completions(self, request: dict) -> PreprocessedRequest:
        from .validate import validate_request

        validate_request(request, "completions")
        prompt = request.get("prompt")
        if prompt is None:
            raise RequestError("'prompt' is required")
        if isinstance(prompt, list):
            if prompt and isinstance(prompt[0], int):
                return self._build_from_tokens([int(t) for t in prompt], request)
            if len(prompt) == 1:
                prompt = prompt[0]
            else:
                # OpenAI batch-prompt semantics (one choice per prompt) are
                # not supported yet; rejecting beats silently concatenating.
                raise RequestError(
                    "batched string prompts are not supported; send one "
                    "prompt per request"
                )
        return self._build(str(prompt), request)

    def _image_token_id(self):
        mm = self.card.runtime_config.get("multimodal")
        return int(mm["image_token_id"]) if mm else None

    def _encode_text(self, text: str) -> list[int]:
        """Tokenize text, dropping the image-placeholder id if this model
        has one: the placeholder must ONLY mark image positions — a text
        occurrence would be spliced over with zero embeddings by the
        engine (and corrupt the prefix cache)."""
        ids = self.tokenizer.encode(text)
        img_id = self._image_token_id()
        if img_id is not None:
            ids = [t for t in ids if t != img_id]
        return ids

    def _build(self, prompt: str, request: dict) -> PreprocessedRequest:
        return self._build_from_tokens(self._encode_text(prompt), request)

    def _build_from_tokens(self, token_ids: list[int], request: dict) -> PreprocessedRequest:
        max_context = self.card.context_length
        if len(token_ids) >= max_context:
            raise RequestError(
                f"prompt ({len(token_ids)} tokens) exceeds the model context "
                f"length ({max_context})"
            )
        max_tokens = request.get("max_completion_tokens") or request.get("max_tokens")
        if max_tokens is None:
            max_tokens = min(self.card.max_output_tokens,
                             max_context - len(token_ids))
        max_tokens = min(int(max_tokens), max_context - len(token_ids))
        if max_tokens <= 0:
            raise RequestError("max_tokens must be positive within context length")

        stop = request.get("stop")
        if stop is None:
            stop_strings = []
        elif isinstance(stop, str):
            stop_strings = [stop]
        else:
            stop_strings = [str(s) for s in stop][:8]

        from .validate import validate_logit_bias

        sampling = SamplingOptions(
            max_tokens=max_tokens,
            temperature=float(request.get("temperature", 1.0) or 0.0),
            top_p=float(request.get("top_p", 1.0) or 1.0),
            top_k=int(request.get("top_k", 0) or 0),
            seed=request.get("seed"),
            frequency_penalty=float(request.get("frequency_penalty", 0.0) or 0.0),
            presence_penalty=float(request.get("presence_penalty", 0.0) or 0.0),
            repetition_penalty=float(
                request.get("repetition_penalty", 1.0) or 1.0),
            min_p=float(request.get("min_p", 0.0) or 0.0),
            logprobs=bool(request.get("logprobs", False)),
            top_logprobs=int(request.get("top_logprobs", 0) or 0),
            logit_bias=validate_logit_bias(request.get("logit_bias")),
        )
        # Completions-style `logprobs: N` (an int, not the chat bool) also
        # requests N alternatives per token.
        lp_req = request.get("logprobs", False)
        if isinstance(lp_req, int) and not isinstance(lp_req, bool):
            # Completions-style integer: logprobs: 0 still returns the
            # sampled token's logprob (with zero alternatives).
            sampling.logprobs = True
            sampling.top_logprobs = max(sampling.top_logprobs, int(lp_req))
        from ..engine.sampler import TOP_LOGPROBS_K

        if sampling.top_logprobs > TOP_LOGPROBS_K:
            # The engine returns a fixed top-K per step; silently truncating
            # would hand back a distribution that looks complete but isn't.
            raise RequestError(
                f"top_logprobs={sampling.top_logprobs} exceeds the engine "
                f"maximum of {TOP_LOGPROBS_K}")
        from .protocols import normalize_priority

        try:
            # Multi-tenant QoS wire surface (docs/multi-tenancy.md): the
            # body `priority` field (the x-dynt-priority header is folded
            # into the body by the HTTP layer before preprocessing) and
            # the tenant identity, normalized once here so every queue
            # downstream sees a validated class.
            priority = normalize_priority(request.get("priority"))
        except ValueError as exc:
            raise RequestError(str(exc))
        pre = PreprocessedRequest(
            request_id=new_request_id(),
            token_ids=token_ids,
            sampling=sampling,
            stop=StopConditions(
                stop_token_ids=[],
                stop_strings=stop_strings,
                ignore_eos=bool(request.get("ignore_eos", False)),
                min_tokens=int(request.get("min_tokens", 0) or 0),
            ),
            eos_token_ids=list(self.tokenizer.eos_token_ids),
            model=request.get("model", self.card.name),
            priority=priority,
            # Tenant ids become Prometheus label values: bound the
            # per-request blast radius (strip + truncate). Cardinality
            # itself is the operator's contract — tenant ids should be
            # a bounded, authenticated set (docs/multi-tenancy.md).
            tenant=str(request.get("tenant") or "").strip()[:64],
        )
        nvext = request.get("nvext")
        if isinstance(nvext, dict):
            if isinstance(nvext.get("annotations"), dict):
                pre.annotations.update(nvext["annotations"])
            if nvext.get("priority") is not None:
                pre.annotations["priority"] = nvext["priority"]
            if nvext.get("logits_processors"):
                pre.logits_processors = list(nvext["logits_processors"])
            if nvext.get("guided_decoding"):
                # reference protocol (common.rs GuidedDecodingOptions):
                # exactly one of json / regex / choice is set (validated
                # in llm/validate.py); enforced by the engine-side
                # 'guided' processor (llm/guided.py)
                gd = dict(nvext["guided_decoding"])
                args = {}
                if gd.get("regex") is not None:
                    args["regex"] = gd["regex"]
                elif gd.get("choice") is not None:
                    args["choice"] = list(gd["choice"])
                elif gd.get("json") is not None:
                    js = gd["json"]
                    if js is True or js == "object":
                        args["json_object"] = True
                    else:
                        args["json_schema"] = js
                pre.logits_processors.append(
                    {"name": "guided", "args": args})
        rf = request.get("response_format")
        if isinstance(rf, dict) and rf.get("type") in ("json_object",
                                                       "json_schema"):
            # OpenAI structured outputs ride the same guided processor
            args = {"json_object": True}
            if rf.get("type") == "json_schema":
                schema = (rf.get("json_schema") or {}).get("schema")
                if schema is not None:
                    # {} stays a schema: it permits ANY value, which is
                    # WEAKER than json_object's top-level-object rule
                    args = {"json_schema": schema}
            pre.logits_processors.append({"name": "guided", "args": args})
        tc = request.get("tool_choice")
        forced_name = None
        force_tools = False
        if tc == "required":
            force_tools = True
        elif isinstance(tc, dict) and tc.get("type") == "function":
            force_tools = True
            forced_name = (tc.get("function") or {}).get("name")
        if force_tools:
            # OpenAI tool_choice forcing: constrain the output to a
            # declared function call in the model's tool-parser format
            # (validated in llm/validate.py; the grammar is built by
            # guided.tool_call_regex so the parser extracts it).
            if not self.card.tool_parser:
                raise RequestError(
                    "tool_choice forcing needs a model served with a "
                    "tool parser (--tool-call-parser)")
            if self.card.tool_parser.lower() not in (
                    "hermes", "qwen", "llama3_json", "mistral"):
                # reject HERE (-> 400), not at engine grammar-build time
                raise RequestError(
                    "tool_choice forcing is not supported for tool "
                    f"parser {self.card.tool_parser!r} (hermes/qwen, "
                    "llama3_json, mistral)")
            pre.logits_processors.append({"name": "guided", "args": {
                "tool_call": {"format": self.card.tool_parser,
                              "tools": request.get("tools") or [],
                              "name": forced_name}}})
        return pre


class DeltaGenerator:
    """Backward edge: EngineOutput stream -> OpenAI SSE chunk dicts, with
    incremental detokenization and stop-string jailing."""

    def __init__(
        self,
        preprocessor: OpenAIPreprocessor,
        request: PreprocessedRequest,
        kind: str = "chat",  # chat | completions
        tool_parser: Optional[str] = None,
        reasoning_parser: Optional[str] = None,
    ) -> None:
        from ..parsers import make_reasoning_parser, make_tool_parser

        self.pre = preprocessor
        self.request = request
        self.kind = kind
        self.chunk_id = openai_chunk_id()
        self.created = now_unix()
        self.detok = IncrementalDetokenizer(preprocessor.tokenizer)
        self.completion_tokens = 0
        self.finish_reason: Optional[str] = None
        self.stop_sequence_hit: Optional[str] = None  # which stop string fired
        self._jail = ""  # text held back: may be a prefix of a stop string
        self._stopped = False
        self._role_sent = False
        self.full_text = ""
        self.full_reasoning = ""
        self.tool_calls: list = []
        # OpenAI-shape logprob entries, one per generated token (populated
        # only when the request asked for logprobs; ref: perf/logprobs.rs
        # consumes these streams)
        self.logprob_entries: list[dict] = []
        # Output parsers (chat only; ref: chat_completions/jail.rs wiring)
        self._reasoning = (make_reasoning_parser(reasoning_parser)
                           if kind == "chat" else None)
        self._tools = (make_tool_parser(tool_parser)
                       if kind == "chat" else None)

    # stop-string handling ------------------------------------------------

    def _filter_stop(self, text: str, final: bool) -> tuple[str, bool]:
        """Returns (emit_text, hit_stop). Holds back possible stop prefixes."""
        stops = self.request.stop.stop_strings
        if not stops:
            return text, False
        buf = self._jail + text
        # Full stop match?
        earliest = None
        for stop in stops:
            idx = buf.find(stop)
            if idx != -1 and (earliest is None or idx < earliest):
                earliest = idx
                self.stop_sequence_hit = stop
        if earliest is not None:
            self._jail = ""
            return buf[:earliest], True
        if final:
            self._jail = ""
            return buf, False
        # Hold back the longest tail that is a proper prefix of any stop.
        hold = 0
        for stop in stops:
            for k in range(min(len(stop) - 1, len(buf)), 0, -1):
                if buf.endswith(stop[:k]):
                    hold = max(hold, k)
                    break
        self._jail = buf[len(buf) - hold :] if hold else ""
        return buf[: len(buf) - hold] if hold else buf, False

    # chunk construction --------------------------------------------------

    def _chunk(self, delta: dict, finish_reason: Optional[str]) -> dict:
        if self.kind == "chat":
            return {
                "id": self.chunk_id,
                "object": "chat.completion.chunk",
                "created": self.created,
                "model": self.request.model,
                "choices": [{
                    "index": 0,
                    "delta": delta,
                    "finish_reason": finish_reason,
                }],
            }
        return {
            "id": self.chunk_id,
            "object": "text_completion",
            "created": self.created,
            "model": self.request.model,
            "choices": [{
                "index": 0,
                "text": delta.get("content", ""),
                "finish_reason": finish_reason,
            }],
        }

    def _route(self, text: str, final: bool) -> list[dict]:
        """Route emitted text through reasoning + tool parsers into OpenAI
        delta dicts (ref: parsers crate via chat_completions/jail.rs)."""
        deltas: list[dict] = []
        reason_text, content_text = "", text
        if self._reasoning is not None:
            ev = self._reasoning.push(text)
            if final:
                fin = self._reasoning.finalize()
                ev.reasoning += fin.reasoning
                ev.content += fin.content
            reason_text, content_text = ev.reasoning, ev.content
        if reason_text:
            self.full_reasoning += reason_text
            deltas.append({"reasoning_content": reason_text})
        if self._tools is not None:
            tev = self._tools.push(content_text)
            if final:
                fin = self._tools.finalize()
                tev.content += fin.content
                tev.calls.extend(fin.calls)
            if tev.content:
                self.full_text += tev.content
                deltas.append({"content": tev.content})
            if tev.calls:
                start = len(self.tool_calls)
                payload = [c.to_openai(start + i)
                           for i, c in enumerate(tev.calls)]
                self.tool_calls.extend(tev.calls)
                deltas.append({"tool_calls": payload})
        elif content_text:
            self.full_text += content_text
            deltas.append({"content": content_text})
        return deltas

    def _final_reason(self, reason: str) -> str:
        return "tool_calls" if (self.tool_calls and reason == "stop") \
            else reason

    def on_output(self, output: EngineOutput) -> list[dict]:
        """Convert one engine item into zero or more SSE chunks."""
        if self._stopped:
            return []
        chunks: list[dict] = []
        if output.error:
            self.finish_reason = "error"
            self._stopped = True
            return [self._chunk({}, "error")]
        self.completion_tokens += len(output.token_ids)
        final = output.finish_reason is not None
        ids = output.token_ids
        trimmed_eos = (output.finish_reason == "stop" and ids
                       and (ids[-1] in self.request.eos_token_ids
                            or ids[-1] in self.request.stop.stop_token_ids))
        if trimmed_eos:
            # the terminating eos/stop TOKEN is not content (HF
            # tokenizers render it as "" via skip_special_tokens, but
            # e.g. the byte tokenizer names its specials)
            ids = ids[:-1]
        new_lp_entries: list[dict] = []
        if output.logprobs is not None:
            before = len(self.logprob_entries)
            self._collect_logprobs(output)
            new_lp_entries = self.logprob_entries[before:]
            if trimmed_eos and new_lp_entries:
                # keep logprob entries 1:1 with CONTENT tokens (OpenAI
                # emits no entry for the stop token)
                new_lp_entries.pop()
                self.logprob_entries.pop()
        text = self.detok.push(ids)
        if final:
            text += self.detok.flush()
        emit, hit_stop = self._filter_stop(text, final)
        for delta in self._route(emit, final or hit_stop):
            if self.kind == "chat" and not self._role_sent:
                delta["role"] = "assistant"
                self._role_sent = True
            chunks.append(self._chunk(delta, None))
        if hit_stop:
            self.finish_reason = self._final_reason("stop")
            self._stopped = True
            chunks.append(self._chunk({}, self.finish_reason))
        elif final:
            self.finish_reason = self._final_reason(output.finish_reason)
            self._stopped = True
            chunks.append(self._chunk({}, self.finish_reason))
        if new_lp_entries and chunks:
            # Streamed logprobs ride the first chunk of this engine item
            # (token-aligned; OpenAI streams them per chunk the same way).
            if self.kind == "chat":
                chunks[0]["choices"][0]["logprobs"] = {
                    "content": new_lp_entries}
            else:
                chunks[0]["choices"][0]["logprobs"] = \
                    self._completions_lp_block(new_lp_entries)
        return chunks

    def _collect_logprobs(self, output) -> None:
        decode = self.pre.tokenizer.decode
        for j, tid in enumerate(output.token_ids):
            entry = {
                "token": decode([tid]),
                "logprob": float(output.logprobs[j]),
            }
            if output.top_logprobs:
                entry["top_logprobs"] = [
                    {"token": decode([int(alt_id)]),
                     "logprob": float(alt_lp)}
                    for alt_id, alt_lp in output.top_logprobs[j]
                ]
            self.logprob_entries.append(entry)

    @staticmethod
    def _completions_lp_block(entries: list[dict]) -> dict:
        return {
            "tokens": [e["token"] for e in entries],
            "token_logprobs": [e["logprob"] for e in entries],
            "top_logprobs": [
                {alt["token"]: alt["logprob"]
                 for alt in e.get("top_logprobs", [])} or None
                for e in entries
            ],
        }

    def logprobs_block(self):
        """OpenAI response logprobs object for this stream, or None."""
        if not self.logprob_entries:
            return None
        if self.kind == "chat":
            return {"content": self.logprob_entries}
        return self._completions_lp_block(self.logprob_entries)

    def usage(self) -> dict:
        return {
            "prompt_tokens": len(self.request.token_ids),
            "completion_tokens": self.completion_tokens,
            "total_tokens": len(self.request.token_ids) + self.completion_tokens,
        }

    def final_response(self) -> dict:
        """Non-streaming aggregate response."""
        if self.kind == "chat":
            message: dict = {"role": "assistant", "content": self.full_text}
            if self.full_reasoning:
                message["reasoning_content"] = self.full_reasoning
            if self.tool_calls:
                message["tool_calls"] = [
                    {k: v for k, v in c.to_openai(i).items() if k != "index"}
                    for i, c in enumerate(self.tool_calls)]
                if not self.full_text:
                    message["content"] = None
            choice = {
                "index": 0,
                "message": message,
                "finish_reason": self.finish_reason or "stop",
            }
            if self.logprob_entries:
                choice["logprobs"] = self.logprobs_block()
            return {
                "id": self.chunk_id,
                "object": "chat.completion",
                "created": self.created,
                "model": self.request.model,
                "choices": [choice],
                "usage": self.usage(),
            }
        choice = {
            "index": 0,
            "text": self.full_text,
            "finish_reason": self.finish_reason or "stop",
        }
        if self.logprob_entries:
            choice["logprobs"] = self.logprobs_block()
        return {
            "id": self.chunk_id,
            "object": "text_completion",
            "created": self.created,
            "model": self.request.model,
            "choices": [choice],
            "usage": self.usage(),
        }
