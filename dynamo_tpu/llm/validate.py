"""OpenAI request validation: unsupported-field tracking + range checks.

The reference captures unknown request fields in a serde catch-all and
rejects them with 400 "Unsupported parameter" instead of silently
dropping them (ref: lib/llm/src/protocols/openai/{completions.rs:44,422,
validate.rs:101}, http/service/openai.rs:2413 tests) — a client sending
`response_format` for JSON mode must learn it is not honored, not
receive confidently wrong output. Known fields get the same range
validation the reference applies (validate.rs temperature/top_p/
penalties/logit_bias/n).
"""

from __future__ import annotations

from typing import Any, Optional

from .preprocessor import RequestError

# Fields consumed by the preprocessor/HTTP layer for each endpoint kind.
# Anything else in the request body is an unsupported parameter.
_COMMON_FIELDS = {
    "model", "stream", "stream_options", "max_tokens",
    "max_completion_tokens", "temperature", "top_p", "top_k", "seed",
    "frequency_penalty", "presence_penalty", "repetition_penalty",
    "min_p", "min_tokens", "logprobs", "top_logprobs",
    "stop", "ignore_eos", "n", "user", "logit_bias", "metadata", "nvext",
    # Multi-tenant QoS (docs/multi-tenancy.md): priority class
    # (interactive | standard | batch; value validated in the
    # preprocessor) and tenant identity. Top-level on every
    # completion-shaped endpoint; the x-dynt-priority /
    # x-dynt-tenant-id headers fold into these fields.
    "priority", "tenant",
}
CHAT_FIELDS = _COMMON_FIELDS | {
    "messages", "tools", "tool_choice", "response_format",
    "parallel_tool_calls",
    # Session tier (docs/prompt-caching.md): session affinity id and a
    # whole-prompt cache marker. Accepted regardless of
    # DYNT_SESSION_ENABLE — the operator switch must not turn existing
    # clients' requests into 400s; per-message cache_control markers
    # live inside message/content dicts and are not top-level fields.
    "session_id", "cache_control",
}
COMPLETION_FIELDS = _COMMON_FIELDS | {"prompt", "echo", "suffix"}

# nvext is our extension namespace (the reference's NvExt analog).
NVEXT_FIELDS = {"annotations", "priority", "logits_processors",
                "guided_decoding"}


def _reject_unknown(body: dict, allowed: set) -> None:
    unknown = sorted(k for k in body if k not in allowed)
    if unknown:
        raise RequestError(
            "Unsupported parameter: "
            + ", ".join(f"'{k}'" for k in unknown))


def _check_range(body: dict, field: str, lo: float, hi: float) -> None:
    val = body.get(field)
    if val is None:
        return
    try:
        f = float(val)
    except (TypeError, ValueError):
        raise RequestError(f"'{field}' must be a number") from None
    if not (lo <= f <= hi):
        raise RequestError(
            f"'{field}' must be between {lo} and {hi}, got {f}")


def validate_logit_bias(raw: Any) -> Optional[dict[int, float]]:
    """OpenAI logit_bias: {token_id: bias in [-100, 100]}. Returns the
    parsed map (int keys) or None."""
    if raw is None:
        return None
    if not isinstance(raw, dict):
        raise RequestError("'logit_bias' must be an object")
    parsed: dict[int, float] = {}
    for key, val in raw.items():
        try:
            token_id = int(key)
        except (TypeError, ValueError):
            raise RequestError(
                f"'logit_bias' key {key!r} is not a token id") from None
        if token_id < 0:
            # Negative ids would wrap via numpy indexing and bias the
            # WRONG token — the silent-wrong-output class this module
            # exists to prevent.
            raise RequestError(
                f"'logit_bias' key {token_id} is not a valid token id")
        try:
            bias = float(val)
        except (TypeError, ValueError):
            raise RequestError(
                f"'logit_bias' value for {key!r} is not a number") from None
        if not (-100.0 <= bias <= 100.0):
            raise RequestError(
                f"'logit_bias' value for token {token_id} must be in "
                f"[-100, 100], got {bias}")
        parsed[token_id] = bias
    return parsed or None


def validate_request(body: dict, kind: str) -> None:
    """Raise RequestError (-> HTTP 400) for unsupported or out-of-range
    fields. kind: "chat" | "completions"."""
    if not isinstance(body, dict):
        raise RequestError("request body must be a JSON object")
    allowed = CHAT_FIELDS if kind == "chat" else COMPLETION_FIELDS
    _reject_unknown(body, allowed)

    _check_range(body, "temperature", 0.0, 2.0)
    _check_range(body, "top_p", 0.0, 1.0)
    _check_range(body, "frequency_penalty", -2.0, 2.0)
    _check_range(body, "presence_penalty", -2.0, 2.0)
    _check_range(body, "repetition_penalty", 0.01, 10.0)
    _check_range(body, "min_p", 0.0, 1.0)
    mt = body.get("min_tokens")
    if mt is not None:
        if not isinstance(mt, int) or mt < 0:
            raise RequestError("'min_tokens' must be a non-negative "
                               "integer")

    n = body.get("n")
    if n is not None and n != 1:
        raise RequestError("only n=1 is supported")

    top_k = body.get("top_k")
    if top_k is not None:
        try:
            top_k_int = int(top_k)
        except (TypeError, ValueError):
            raise RequestError("'top_k' must be an integer") from None
        if top_k_int < 0:
            raise RequestError("'top_k' must be >= 0")

    stop = body.get("stop")
    if stop is not None and not isinstance(stop, str):
        if not (isinstance(stop, list)
                and all(isinstance(s, str) for s in stop)):
            raise RequestError(
                "'stop' must be a string or an array of strings")

    validate_logit_bias(body.get("logit_bias"))

    rf = body.get("response_format")
    if rf is not None:
        # json_object / json_schema are enforced by the engine-side
        # guided-decoding processor (llm/guided.py); anything else would
        # be silent wrong behavior.
        if not (isinstance(rf, dict)
                and rf.get("type") in ("text", "json_object",
                                       "json_schema")):
            got = rf.get("type") if isinstance(rf, dict) else rf
            raise RequestError(
                f"response_format type {got!r} is not supported "
                "(text, json_object, or json_schema)")
        if isinstance(rf, dict) and rf.get("type") == "json_schema":
            js = rf.get("json_schema")
            if not isinstance(js, dict) or not isinstance(
                    js.get("schema"), dict):
                raise RequestError(
                    "response_format json_schema needs "
                    "{'json_schema': {'schema': {...}}}")

    tc = body.get("tool_choice")
    if tc is not None:
        tools = body.get("tools")
        names = []
        if isinstance(tools, list):
            names = [n for n in ((t.get("function") or {}).get("name")
                                 for t in tools if isinstance(t, dict))
                     if isinstance(n, str) and n]
        if isinstance(tc, str):
            if tc not in ("none", "auto", "required"):
                raise RequestError(
                    "tool_choice must be 'none', 'auto', 'required', or "
                    "{'type': 'function', 'function': {'name': ...}}")
            if tc == "required" and not names:
                raise RequestError(
                    "tool_choice 'required' needs non-empty 'tools'")
        elif isinstance(tc, dict) and tc.get("type") == "function":
            name = (tc.get("function") or {}).get("name")
            if not isinstance(name, str) or not name:
                raise RequestError(
                    "tool_choice function needs a 'name'")
            if name not in names:
                raise RequestError(
                    f"tool_choice function {name!r} is not in 'tools'")
        else:
            raise RequestError(
                "tool_choice must be 'none', 'auto', 'required', or "
                "{'type': 'function', 'function': {'name': ...}}")
        if tc not in ("none", "auto") and isinstance(rf, dict) \
                and rf.get("type") in ("json_object", "json_schema"):
            raise RequestError(
                "tool_choice forcing and response_format "
                "json_object/json_schema cannot be combined")

    gd = (body.get("nvext") or {}).get("guided_decoding") \
        if isinstance(body.get("nvext"), dict) else None
    if gd is not None:
        if not isinstance(gd, dict):
            raise RequestError("nvext.guided_decoding must be an object")
        if isinstance(rf, dict) and rf.get("type") in ("json_object",
                                                       "json_schema"):
            raise RequestError(
                "nvext.guided_decoding and response_format "
                "json_object/json_schema cannot be combined (two "
                "constraints would intersect)")
        if tc is not None and tc not in ("none", "auto"):
            raise RequestError(
                "nvext.guided_decoding and tool_choice forcing cannot "
                "be combined (two constraints would intersect)")
        set_keys = [k for k in ("json", "regex", "choice", "grammar")
                    if gd.get(k) is not None]
        if len(set_keys) != 1:
            raise RequestError(
                "nvext.guided_decoding needs exactly one of json / "
                "regex / choice")
        if set_keys == ["json"] and not (
                isinstance(gd["json"], dict) or gd["json"] is True
                or gd["json"] == "object"):
            raise RequestError(
                "guided_decoding.json must be a JSON-schema object, "
                "true, or 'object'")
        if set_keys == ["grammar"]:
            raise RequestError(
                "guided_decoding.grammar (EBNF) is not supported; use "
                "json, regex, or choice")
        if set_keys == ["choice"] and not (
                isinstance(gd["choice"], list) and gd["choice"]
                and all(isinstance(c, str) for c in gd["choice"])):
            raise RequestError(
                "guided_decoding.choice must be a non-empty string list")
        if set_keys == ["regex"] and not isinstance(gd["regex"], str):
            raise RequestError("guided_decoding.regex must be a string")

    suffix = body.get("suffix")
    if suffix is not None and suffix != "":
        raise RequestError("'suffix' is not supported")

    if body.get("echo"):
        raise RequestError("'echo' is not supported")

    nvext = body.get("nvext")
    if nvext is not None:
        if not isinstance(nvext, dict):
            raise RequestError("'nvext' must be an object")
        unknown = sorted(k for k in nvext if k not in NVEXT_FIELDS)
        if unknown:
            raise RequestError(
                "Unsupported nvext parameter: "
                + ", ".join(f"'{k}'" for k in unknown))
        procs = nvext.get("logits_processors")
        if procs is not None:
            if not isinstance(procs, list):
                raise RequestError("'nvext.logits_processors' must be a list")
            for spec in procs:
                if not (isinstance(spec, str)
                        or (isinstance(spec, dict) and "name" in spec)):
                    raise RequestError(
                        "each logits processor must be a name or an "
                        "object with a 'name'")
