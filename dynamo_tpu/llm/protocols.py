"""LLM serving protocols: the token-level request/response contract.

The frontend preprocessor lowers OpenAI-shape requests into a
PreprocessedRequest of token ids + sampling + stop conditions, which is what
crosses the request plane to workers (ref: lib/llm/src/preprocessor.rs
OpenAIPreprocessor -> PreprocessedRequest; protocols/common.rs). Workers
stream back token deltas; the Backend operator detokenizes incrementally
(ref: lib/llm/src/backend.rs:56).
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Any, Optional


@dataclasses.dataclass
class SamplingOptions:
    max_tokens: int = 256
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    seed: Optional[int] = None
    frequency_penalty: float = 0.0
    presence_penalty: float = 0.0
    logprobs: bool = False
    top_logprobs: int = 0
    # OpenAI logit_bias: {token_id: additive bias}; applied via the
    # host logits-processor path (llm/logits_processing.py)
    logit_bias: Optional[dict] = None
    # HF-semantics multiplicative repetition penalty (1.0 = off) and
    # vLLM-style min_p nucleus floor (0.0 = off); both enforced via the
    # host logits-processor path (ref: protocols/common.rs:305,323)
    repetition_penalty: float = 1.0
    min_p: float = 0.0

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, data: dict) -> "SamplingOptions":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in (data or {}).items() if k in fields})


@dataclasses.dataclass
class StopConditions:
    stop_token_ids: list[int] = dataclasses.field(default_factory=list)
    stop_strings: list[str] = dataclasses.field(default_factory=list)
    ignore_eos: bool = False
    # suppress EOS until this many tokens are generated (ref:
    # protocols/common.rs:246 — "to ignore_eos, set min_tokens")
    min_tokens: int = 0

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, data: dict) -> "StopConditions":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in (data or {}).items() if k in fields})


# Multi-tenant QoS (docs/multi-tenancy.md): the priority classes a
# request may declare on the wire (`priority` body field or
# x-dynt-priority header), strongest first. Class is STRICT at every
# queue — interactive never parks behind batch — and batch is the
# preemption donor under interactive pressure.
PRIORITY_CLASSES = ("interactive", "standard", "batch")
_CLASS_RANK = {"interactive": 2, "standard": 1, "batch": 0}


def class_rank(priority: str) -> int:
    """Numeric rank of a priority class (higher schedules first).
    Unknown strings rank as `standard` — rank is an ordering helper,
    validation happens at the preprocessor edge."""
    return _CLASS_RANK.get(priority, _CLASS_RANK["standard"])


def normalize_priority(raw) -> str:
    """Validate + normalize a wire priority value. None/"" defaults to
    `standard`; anything else must name a known class."""
    if raw is None or raw == "":
        return "standard"
    val = str(raw).strip().lower()
    if val not in PRIORITY_CLASSES:
        raise ValueError(
            f"unknown priority {raw!r} (expected one of "
            f"{'|'.join(PRIORITY_CLASSES)})")
    return val


@dataclasses.dataclass
class PreprocessedRequest:
    """What the frontend sends to a worker (ModelInput.Tokens)."""

    request_id: str
    token_ids: list[int]
    sampling: SamplingOptions
    stop: StopConditions
    eos_token_ids: list[int] = dataclasses.field(default_factory=list)
    model: str = ""
    # Router-injected: disaggregated prefill handoff (ref: section 3.4)
    disaggregated_params: Optional[dict] = None
    # Echo of prior output tokens on migration (ref: migration.rs retains
    # generated tokens when replaying to a new worker)
    prior_output_tokens: list[int] = dataclasses.field(default_factory=list)
    annotations: dict = dataclasses.field(default_factory=dict)
    # Multi-LoRA: adapter to apply (frontend resolves model=<adapter-name>
    # against worker cards; ref: lib/llm/src/lora.rs routing)
    lora_name: Optional[str] = None
    # Multimodal: content identity of each image (salts KV hashes — same
    # placeholder tokens with different images must never share KV) and
    # the encoder's output rows spliced at placeholder positions
    # (wire: {"shape": [n, H], "data": f32 bytes})
    media_hashes: list[int] = dataclasses.field(default_factory=list)
    media_embeddings: Optional[dict] = None
    # Logits-processor specs (names or {"name","args"}) resolved against
    # the worker's registry (llm/logits_processing.py)
    logits_processors: list = dataclasses.field(default_factory=list)
    # Session tier (dynamo_tpu/session): client-declared cacheable
    # prefix boundaries as TOKEN counts into token_ids (ascending; each
    # floors to full blocks before hashing), and the session-affinity
    # id. The worker pins the anchored blocks into its KVBM tiers;
    # routers key residency on session_id. Both empty = the request is
    # wire-identical to the pre-session-tier protocol. cache_ttl is the
    # client-requested lease TTL (seconds) of the longest anchor — the
    # worker's KVBM pin honors it instead of defaulting to the system
    # ceiling (still clamped to DYNT_PIN_TTL_SECS).
    cache_anchors: list[int] = dataclasses.field(default_factory=list)
    cache_ttl: Optional[float] = None
    session_id: Optional[str] = None
    # Multi-tenant QoS (docs/multi-tenancy.md): the normalized priority
    # class (interactive | standard | batch; preprocessor-validated) and
    # the tenant identity (x-dynt-tenant-id / `tenant` body field; ""
    # = untagged). Both default-valued = wire-identical to the pre-QoS
    # protocol. Priority is class-STRICT at every queue and on the chip
    # (batch decode slots are the preemption donors); tenant keys the
    # fair-share TenantLedger at the admission edges and labels the
    # shed/goodput metrics.
    priority: str = "standard"
    tenant: str = ""
    # End-to-end budget (runtime/resilience.py Deadline), stamped by the
    # frontend at admission. NOT serialized by to_wire: it crosses the
    # request plane as the x-dynt-deadline-ms header (re-encoded as
    # remaining-ms per hop), and the worker side reads it from its
    # RequestContext — this field only rides the in-process pipeline
    # (router, migration, prefill legs).
    deadline: Optional[Any] = None

    def kv_salt(self) -> Optional[int]:
        """Perturbs block-hash chaining for anything beyond token ids that
        changes KV content (adapter weights, image embeddings). Media
        hashes are CHAINED (order-sensitive): XOR would let swapped or
        repeated images cancel out and share KV with the wrong content."""
        from dynamo_tpu.tokens import lora_id_of

        salt = lora_id_of(self.lora_name)
        if self.media_hashes:
            import xxhash

            buf = b"".join(
                (int(h) & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
                for h in self.media_hashes)
            salt = xxhash.xxh64_intdigest(
                buf, seed=(salt or 0) & 0xFFFFFFFFFFFFFFFF)
        return salt

    def to_wire(self) -> dict:
        out = {
            "request_id": self.request_id,
            "token_ids": self.token_ids,
            "sampling": self.sampling.to_wire(),
            "stop": self.stop.to_wire(),
            "eos_token_ids": self.eos_token_ids,
            "model": self.model,
            "annotations": self.annotations,
        }
        if self.disaggregated_params is not None:
            out["disaggregated_params"] = self.disaggregated_params
        if self.prior_output_tokens:
            out["prior_output_tokens"] = self.prior_output_tokens
        if self.lora_name:
            out["lora_name"] = self.lora_name
        if self.media_hashes:
            out["media_hashes"] = self.media_hashes
        if self.media_embeddings is not None:
            out["media_embeddings"] = self.media_embeddings
        if self.logits_processors:
            out["logits_processors"] = self.logits_processors
        if self.cache_anchors:
            out["cache_anchors"] = self.cache_anchors
        if self.cache_ttl:
            out["cache_ttl"] = self.cache_ttl
        if self.session_id:
            out["session_id"] = self.session_id
        if self.priority != "standard":
            out["priority"] = self.priority
        if self.tenant:
            out["tenant"] = self.tenant
        return out

    @classmethod
    def from_wire(cls, data: dict) -> "PreprocessedRequest":
        return cls(
            request_id=data.get("request_id") or uuid.uuid4().hex,
            token_ids=list(data.get("token_ids") or []),
            sampling=SamplingOptions.from_wire(data.get("sampling") or {}),
            stop=StopConditions.from_wire(data.get("stop") or {}),
            eos_token_ids=list(data.get("eos_token_ids") or []),
            model=data.get("model", ""),
            disaggregated_params=data.get("disaggregated_params"),
            prior_output_tokens=list(data.get("prior_output_tokens") or []),
            annotations=data.get("annotations") or {},
            lora_name=data.get("lora_name"),
            media_hashes=list(data.get("media_hashes") or []),
            media_embeddings=data.get("media_embeddings"),
            logits_processors=list(data.get("logits_processors") or []),
            cache_anchors=list(data.get("cache_anchors") or []),
            cache_ttl=data.get("cache_ttl"),
            session_id=data.get("session_id"),
            priority=data.get("priority") or "standard",
            tenant=data.get("tenant") or "",
        )


@dataclasses.dataclass
class EngineOutput:
    """One streamed item from a worker: newly generated token ids (usually
    one for decode, many for a final chunk) plus terminal state."""

    token_ids: list[int] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None  # stop | length | error | cancelled
    # Cumulative count of prompt tokens actually processed (first chunk)
    prompt_tokens: Optional[int] = None
    logprobs: Optional[list[float]] = None
    # Per emitted token: [[token_id, logprob], ...] alternatives (top-K
    # from the raw model distribution)
    top_logprobs: Optional[list[list[list[float]]]] = None
    # Disagg: prefill worker returns KV handoff params instead of decoding
    kv_transfer_params: Optional[dict] = None
    # Embedding requests return a pooled vector instead of tokens
    embedding: Optional[list[float]] = None
    error: Optional[str] = None

    def to_wire(self) -> dict:
        out: dict = {"t": self.token_ids}
        if self.finish_reason is not None:
            out["f"] = self.finish_reason
        if self.prompt_tokens is not None:
            out["p"] = self.prompt_tokens
        if self.logprobs is not None:
            out["lp"] = self.logprobs
        if self.top_logprobs is not None:
            out["tlp"] = self.top_logprobs
        if self.kv_transfer_params is not None:
            out["kv"] = self.kv_transfer_params
        if self.embedding is not None:
            out["emb"] = self.embedding
        if self.error is not None:
            out["err"] = self.error
        return out

    @classmethod
    def from_wire(cls, data: dict) -> "EngineOutput":
        return cls(
            token_ids=list(data.get("t") or []),
            finish_reason=data.get("f"),
            prompt_tokens=data.get("p"),
            logprobs=data.get("lp"),
            top_logprobs=data.get("tlp"),
            kv_transfer_params=data.get("kv"),
            embedding=data.get("emb"),
            error=data.get("err"),
        )


def new_request_id() -> str:
    return uuid.uuid4().hex


def openai_chunk_id() -> str:
    return f"chatcmpl-{uuid.uuid4().hex[:24]}"


def now_unix() -> int:
    return int(time.time())
