"""Pluggable logits processors (ref: lib/bindings/python/src/dynamo/
logits_processing/base.py BaseLogitsProcessor + examples/).

The reference protocol is a per-request callable that mutates the
next-token logits in place given the tokens generated so far. On TPU
the decode hot path keeps sampling inside the compiled step so only
token ids cross device->host; requests that attach a processor opt into
a slower escape hatch: the engine switches those steps to a variant
that also returns the raw logits rows, applies the processors on host
(numpy, in place — same contract as the reference), re-samples on host,
and feeds the chosen token back into the next step. The cost (a [V]
f32 readback per step) is paid only by requests that ask for it, which
is the reference's stance too (its processors are Python callbacks on
the engine step path).

`logit_bias` (OpenAI API field) is implemented as an implicit processor
on the same path.

Processors are registered per deployment (worker startup) and selected
per request via `nvext.logits_processors: [{"name": ..., "args": {}}]`.
Factories receive the tokenizer when they declare it, mirroring the
reference examples (HelloWorldLogitsProcessor takes the tokenizer).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Optional, Protocol, Sequence,\
    runtime_checkable

import numpy as np


@runtime_checkable
class BaseLogitsProcessor(Protocol):
    """Per-request processor: mutate `logits` ([V] float32 numpy row for
    the next token) in place. `input_ids` are the tokens generated so
    far (ref: logits_processing/base.py — same signature with a torch
    tensor; numpy here)."""

    def __call__(self, input_ids: Sequence[int],
                 logits: np.ndarray) -> None: ...


_REGISTRY: dict[str, Callable[..., BaseLogitsProcessor]] = {}


def register_processor(name: str,
                       factory: Callable[..., BaseLogitsProcessor]) -> None:
    """Register a processor factory under `name`. The factory is called
    once per request with the request's `args` dict (plus `tokenizer=`
    when its signature accepts it), so processors can keep per-request
    state (the reference's HelloWorld example counts steps)."""
    _REGISTRY[name] = factory


def registered_processors() -> list[str]:
    return sorted(_REGISTRY)


def resolve_processors(
    specs: Optional[list],
    tokenizer: Any = None,
) -> list[BaseLogitsProcessor]:
    """Instantiate processors for one request from nvext specs
    (names or {"name":..., "args": {...}}). Unknown names raise
    ValueError (surfaced as a 400 by the worker): a silently dropped
    processor would return unconstrained output the client believes is
    constrained."""
    out: list[BaseLogitsProcessor] = []
    for spec in specs or []:
        if isinstance(spec, str):
            name, args = spec, {}
        else:
            name, args = spec["name"], dict(spec.get("args") or {})
        factory = _REGISTRY.get(name)
        if factory is None:
            raise ValueError(
                f"unknown logits processor {name!r}; registered: "
                f"{registered_processors()}")
        params = inspect.signature(factory).parameters
        if "tokenizer" in params and tokenizer is not None:
            args.setdefault("tokenizer", tokenizer)
        out.append(factory(**args))
    return out


class LogitBiasProcessor:
    """OpenAI `logit_bias`: additive bias per token id."""

    def __init__(self, bias: dict[int, float]) -> None:
        self._ids = np.fromiter(bias.keys(), np.int64, len(bias))
        self._vals = np.fromiter(bias.values(), np.float32, len(bias))

    def __call__(self, input_ids: Sequence[int],
                 logits: np.ndarray) -> None:
        mask = self._ids < logits.shape[-1]
        np.add.at(logits, self._ids[mask], self._vals[mask])


# -- built-in examples (ref: logits_processing/examples/) -------------------


class ForcedResponseProcessor:
    """Force an exact token sequence then EOS (ref: examples/
    hello_world.py HelloWorldLogitsProcessor — the canonical "did my
    processor actually run" probe)."""

    def __init__(self, token_ids: list[int], eos_id: int) -> None:
        self.token_ids = [int(t) for t in token_ids]
        self.eos_id = int(eos_id)
        self.state = 0

    def __call__(self, input_ids: Sequence[int],
                 logits: np.ndarray) -> None:
        want = (self.token_ids[self.state]
                if self.state < len(self.token_ids) else self.eos_id)
        logits[:] = -np.inf
        logits[want] = 0.0
        self.state += 1


class TemperatureProcessor:
    """Logit-side temperature scaling (ref: examples/temperature.py)."""

    def __init__(self, temperature: float = 1.0) -> None:
        if temperature <= 0:
            raise ValueError("temperature must be positive")
        self.temperature = float(temperature)

    def __call__(self, input_ids: Sequence[int],
                 logits: np.ndarray) -> None:
        logits /= self.temperature


class PenaltyProcessor:
    """OpenAI frequency/presence penalties over the tokens generated so
    far (the engine routes penalty requests through the host path so the
    penalties are actually applied — the compiled step samples from the
    raw distribution)."""

    def __init__(self, frequency_penalty: float = 0.0,
                 presence_penalty: float = 0.0) -> None:
        self.frequency_penalty = float(frequency_penalty)
        self.presence_penalty = float(presence_penalty)

    def __call__(self, input_ids: Sequence[int],
                 logits: np.ndarray) -> None:
        if not len(input_ids):
            return
        ids, counts = np.unique(np.asarray(input_ids, np.int64),
                                return_counts=True)
        keep = ids < logits.shape[-1]
        ids, counts = ids[keep], counts[keep]
        logits[ids] -= (self.frequency_penalty * counts
                        + self.presence_penalty)


class BanTokensProcessor:
    """Never emit the given token ids."""

    def __init__(self, token_ids: list[int]) -> None:
        self.token_ids = [int(t) for t in token_ids]

    def __call__(self, input_ids: Sequence[int],
                 logits: np.ndarray) -> None:
        logits[self.token_ids] = -np.inf


class RepetitionPenaltyProcessor:
    """HF-semantics multiplicative repetition penalty over the prompt AND
    every token generated so far: positive logits divide by the penalty,
    negative multiply (ref protocol: protocols/common.rs
    repetition_penalty; HF penalizes prompt ∪ generated)."""

    def __init__(self, penalty: float,
                 prompt_ids: Optional[Sequence[int]] = None) -> None:
        if penalty <= 0:
            raise ValueError("repetition_penalty must be positive")
        self.penalty = float(penalty)
        self._prompt_ids = np.unique(np.asarray(
            list(prompt_ids) if prompt_ids is not None else [], np.int64))

    def __call__(self, input_ids: Sequence[int],
                 logits: np.ndarray) -> None:
        if self.penalty == 1.0:
            return
        generated = np.asarray(list(input_ids), np.int64)
        ids = np.union1d(self._prompt_ids, generated)
        ids = ids[ids < logits.shape[-1]]
        if not len(ids):
            return
        vals = logits[ids]
        logits[ids] = np.where(vals > 0, vals / self.penalty,
                               vals * self.penalty)


class MinTokensProcessor:
    """Ban EOS/stop tokens until `min_tokens` have been generated (ref
    protocol: protocols/common.rs min_tokens)."""

    def __init__(self, min_tokens: int, eos_ids: Sequence[int]) -> None:
        self.min_tokens = int(min_tokens)
        self.eos_ids = [int(e) for e in eos_ids]

    def __call__(self, input_ids: Sequence[int],
                 logits: np.ndarray) -> None:
        if len(input_ids) < self.min_tokens:
            for e in self.eos_ids:
                if e < logits.shape[-1]:
                    logits[e] = -np.inf


class MinPProcessor:
    """vLLM-style min_p: mask tokens whose post-temperature probability
    is below min_p * max_prob (ref protocol: common.rs min_p)."""

    def __init__(self, min_p: float, temperature: float = 1.0) -> None:
        if not 0.0 < min_p <= 1.0:
            raise ValueError("min_p must be in (0, 1]")
        self.min_p = float(min_p)
        self.temperature = max(float(temperature), 1e-6)

    def __call__(self, input_ids: Sequence[int],
                 logits: np.ndarray) -> None:
        scaled = logits.astype(np.float64) / self.temperature
        scaled -= scaled.max()
        probs = np.exp(scaled)
        probs /= probs.sum()
        logits[probs < self.min_p * probs.max()] = -np.inf


def _guided_factory(tokenizer=None, **kwargs):
    from .guided import make_guided_processor

    return make_guided_processor(tokenizer=tokenizer, **kwargs)


register_processor("forced_response", ForcedResponseProcessor)
register_processor("temperature", TemperatureProcessor)
register_processor("ban_tokens", BanTokensProcessor)
# Structured outputs (llm/guided.py): regex / choice / json_schema /
# json_object constraints as a DFA-masking processor — the engine-side
# enforcement of the reference's guided_decoding protocol options.
register_processor("guided", _guided_factory)


def host_sample(logits: np.ndarray, temperature: float, top_p: float,
                top_k: int, seed: Optional[int], step: int) -> int:
    """Sample from a processed logits row on host, mirroring the
    compiled sampler's semantics (greedy at temperature 0; top-k/top-p
    truncation; seeded draws keyed by (seed, step) so a fixed request
    seed reproduces its stream)."""
    if temperature <= 0.0:
        return int(np.argmax(logits))
    scaled = logits.astype(np.float64) / temperature
    top_k = min(int(top_k or 0), len(scaled))  # clamp like the device
    if top_k > 0:                              # sampler's jnp.clip
        kth = np.partition(scaled, -top_k)[-top_k]
        scaled = np.where(scaled >= kth, scaled, -np.inf)
    if top_p < 1.0:
        order = np.argsort(scaled)[::-1]
        probs = np.exp(scaled[order] - np.max(scaled))
        probs /= probs.sum()
        keep = np.cumsum(probs) - probs < top_p
        cut = np.full_like(scaled, -np.inf)
        cut[order[keep]] = scaled[order[keep]]
        scaled = cut
    probs = np.exp(scaled - np.max(scaled))
    probs /= probs.sum()
    rng = np.random.default_rng(
        (0 if seed is None else int(seed)) * 1_000_003 + step)
    return int(rng.choice(len(probs), p=probs))
