"""LLM serving layer (ref layer L1: lib/llm)."""

from .engine import KvRouterEngine, Migration, RouterEngine, TokenEngine
from .http_service import HttpService
from .manager import ModelEntry, ModelManager, ModelWatcher
from .model_card import (
    CHAT,
    COMPLETIONS,
    EMBEDDINGS,
    INPUT_TEXT,
    INPUT_TOKENS,
    PREFILL,
    ModelDeploymentCard,
    publish_card,
    unpublish_card,
)
from .preprocessor import DeltaGenerator, OpenAIPreprocessor, RequestError
from .protocols import (
    EngineOutput,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
    new_request_id,
)
from .tokenizer import (
    ByteTokenizer,
    HfTokenizer,
    IncrementalDetokenizer,
    Tokenizer,
    load_tokenizer,
)

__all__ = [
    "ByteTokenizer",
    "CHAT",
    "COMPLETIONS",
    "DeltaGenerator",
    "EMBEDDINGS",
    "EngineOutput",
    "HfTokenizer",
    "HttpService",
    "INPUT_TEXT",
    "INPUT_TOKENS",
    "IncrementalDetokenizer",
    "KvRouterEngine",
    "Migration",
    "ModelDeploymentCard",
    "ModelEntry",
    "ModelManager",
    "ModelWatcher",
    "OpenAIPreprocessor",
    "PREFILL",
    "PreprocessedRequest",
    "RequestError",
    "RouterEngine",
    "SamplingOptions",
    "StopConditions",
    "TokenEngine",
    "Tokenizer",
    "load_tokenizer",
    "new_request_id",
    "publish_card",
    "unpublish_card",
]
