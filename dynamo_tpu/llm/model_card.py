"""ModelDeploymentCard: everything a frontend needs to serve a model.

Workers publish a card into discovery when they register (ref: lib/llm/src/
model_card.rs:183; attach flow in local_model.rs:427 writes to
v1/mdc/{ns}/{component}/{endpoint}/{instance_id}); frontends' ModelWatcher
builds serving pipelines from it (section 3.1). The card carries model
identity, the tokenizer spec, context/generation limits, and the KV block
size (which must match between router hashing and engine paging).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..runtime.discovery import MODEL_CARD_PREFIX

# Model types (ref: ModelType Chat|Completions|Prefill|Embeddings...)
CHAT = "chat"
COMPLETIONS = "completions"
PREFILL = "prefill"
EMBEDDINGS = "embeddings"
ENCODER = "encoder"  # multimodal encode workers (E of E/P/D)
IMAGE = "image"  # diffusion (image/video generation) workers

# Model input types (ref: ModelInput::{Tokens,Text})
INPUT_TOKENS = "tokens"
INPUT_TEXT = "text"


@dataclasses.dataclass
class ModelDeploymentCard:
    name: str
    model_types: list[str] = dataclasses.field(default_factory=lambda: [CHAT, COMPLETIONS])
    model_input: str = INPUT_TOKENS
    tokenizer: dict = dataclasses.field(default_factory=lambda: {"kind": "byte"})
    context_length: int = 8192
    max_output_tokens: int = 4096
    kv_block_size: int = 16
    chat_template: Optional[str] = None
    # Output parsing (ref: lib/parsers wiring via model card runtime config)
    tool_parser: Optional[str] = None  # hermes|mistral|llama3_json|pythonic
    reasoning_parser: Optional[str] = None  # think|deepseek-r1|granite
    # Serving component this card belongs to
    namespace: str = "dynamo"
    component: str = "backend"
    endpoint: str = "generate"
    # Router hints
    total_kv_blocks: int = 0
    data_parallel_size: int = 1
    runtime_config: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        # Fail at card construction (worker startup / card publish), not per
        # request inside the frontend's delta generator.
        from dynamo_tpu.parsers import REASONING_PARSERS, TOOL_PARSERS
        from dynamo_tpu.tokens import HASH_VERSION

        if self.tool_parser and self.tool_parser.lower() not in TOOL_PARSERS:
            raise ValueError(
                f"unknown tool parser {self.tool_parser!r}; "
                f"one of {sorted(TOOL_PARSERS)}")
        if (self.reasoning_parser
                and self.reasoning_parser.lower() not in REASONING_PARSERS):
            raise ValueError(
                f"unknown reasoning parser {self.reasoning_parser!r}; "
                f"one of {sorted(REASONING_PARSERS)}")
        # KV identities only match between processes on the same hash scheme.
        self.runtime_config.setdefault("kv_hash_version", HASH_VERSION)

    def card_key(self, instance_id: int) -> str:
        return (
            f"{MODEL_CARD_PREFIX}/{self.namespace}/{self.component}/"
            f"{self.endpoint}/{instance_id}"
        )

    @property
    def endpoint_subject(self) -> str:
        return f"{self.namespace}/{self.component}/{self.endpoint}"

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, data: dict) -> "ModelDeploymentCard":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in fields})


async def publish_card(runtime, card: ModelDeploymentCard, instance_id: int) -> None:
    """Attach a model card under the runtime lease (ref: LocalModel.attach)."""
    await runtime.put_leased(card.card_key(instance_id), card.to_wire())


async def unpublish_card(runtime, card: ModelDeploymentCard, instance_id: int) -> None:
    await runtime.delete_leased(card.card_key(instance_id))
