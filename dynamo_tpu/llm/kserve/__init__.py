"""KServe Predict Protocol v2 gRPC frontend (ref: lib/llm/src/grpc/service/
kserve.rs — the reference exposes the same GRPCInferenceService next to the
OpenAI HTTP surface). Messages are generated from inference.proto with protoc
(`protoc --python_out=. inference.proto`); the service wiring is hand-rolled
over grpc.aio generic handlers so no grpc codegen plugin is needed."""

from .service import KServeGrpcService

__all__ = ["KServeGrpcService"]
