"""KServe v2 gRPC inference service over the serving pipeline.

Tensor convention (matches the reference's LLM mapping, kserve.rs):
  inputs:  "text_input" BYTES [1]   — the prompt
           "streaming"  BOOL [1]    — stream tokens (ModelStreamInfer only)
  request parameters: "max_tokens" int64, "temperature" double,
           "top_p" double, "chat" bool (route through the chat template)
  outputs: "text_output" BYTES [1]  — generated text (delta when streaming)

ModelInfer aggregates; ModelStreamInfer streams one response per text delta.
"""

from __future__ import annotations

import asyncio
import time
from typing import AsyncIterator, Optional

import grpc

from ...runtime.flight_recorder import get_recorder
from ...runtime.logging import (current_request_id, current_trace_id,
                                get_logger)
from ...runtime.otel import get_tracer, trace_id_of
from ..manager import ModelManager
from ..preprocessor import DeltaGenerator, RequestError
from . import inference_pb2 as pb

log = get_logger("llm.kserve")


def _grpc_traceparent(context) -> Optional[str]:
    """W3C trace context from the gRPC invocation metadata (the header
    contract is identical to HTTP: lowercase `traceparent` key)."""
    try:
        for key, value in context.invocation_metadata() or ():
            if key == "traceparent":
                return value
    except Exception:  # noqa: BLE001 — metadata is best-effort
        pass
    return None

_SERVICE = "inference.GRPCInferenceService"


def _param(params, name: str, kind: str, default=None):
    p = params.get(name)
    if p is None:
        return default
    return getattr(p, kind)


def _text_response(model: str, request_id: str, text: str) -> pb.ModelInferResponse:
    return pb.ModelInferResponse(
        model_name=model,
        id=request_id,
        outputs=[pb.ModelInferResponse.InferOutputTensor(
            name="text_output", datatype="BYTES", shape=[1],
            contents=pb.InferTensorContents(
                bytes_contents=[text.encode()]),
        )],
    )


class KServeGrpcService:
    def __init__(self, manager: ModelManager, host: str = "0.0.0.0",
                 port: int = 0) -> None:
        self.manager = manager
        self.host = host
        self.port = port
        self._server: Optional[grpc.aio.Server] = None

    # -- request lowering --------------------------------------------------

    async def _entry(self, model_name: str, context):
        # resolve() also matches LoRA adapter names, keeping the gRPC and
        # HTTP surfaces consistent.
        entry, lora = self.manager.resolve(model_name)
        if entry is None:
            # context.abort raises; the await satisfies grpc.aio's contract.
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"model '{model_name}' not found")
        return entry, lora

    async def _preprocess(self, request: pb.ModelInferRequest, context):
        text = None
        for i, tensor in enumerate(request.inputs):
            if tensor.name == "text_input":
                if tensor.contents.bytes_contents:
                    text = tensor.contents.bytes_contents[0].decode()
                elif len(request.raw_input_contents) > i:
                    raw = request.raw_input_contents[i]
                    # raw BYTES tensor: 4-byte LE length prefix + payload
                    text = raw[4:4 + int.from_bytes(raw[:4], "little")].decode()
        if text is None:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "missing 'text_input' BYTES tensor")
        entry, lora = await self._entry(request.model_name, context)
        params = request.parameters
        body = {
            "model": request.model_name,
            "max_tokens": _param(params, "max_tokens", "int64_param"),
            "temperature": _param(params, "temperature", "double_param", 1.0),
            "top_p": _param(params, "top_p", "double_param", 1.0),
        }
        try:
            if _param(params, "chat", "bool_param", False):
                body["messages"] = [{"role": "user", "content": text}]
                preprocessed = entry.preprocessor.preprocess_chat(body)
            else:
                body["prompt"] = text
                preprocessed = entry.preprocessor.preprocess_completions(body)
        except RequestError as exc:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        preprocessed.lora_name = lora
        return entry, preprocessed

    # -- handlers ----------------------------------------------------------

    async def _server_live(self, request, context) -> pb.ServerLiveResponse:
        return pb.ServerLiveResponse(live=True)

    async def _server_ready(self, request, context) -> pb.ServerReadyResponse:
        return pb.ServerReadyResponse(ready=True)

    async def _model_ready(self, request, context) -> pb.ModelReadyResponse:
        entry, _ = self.manager.resolve(request.name)
        return pb.ModelReadyResponse(ready=entry is not None)

    async def _server_metadata(self, request, context) -> pb.ServerMetadataResponse:
        return pb.ServerMetadataResponse(
            name="dynamo_tpu", version="1.0",
            extensions=["model_repository"])

    async def _model_metadata(self, request, context) -> pb.ModelMetadataResponse:
        entry, _ = await self._entry(request.name, context)
        return pb.ModelMetadataResponse(
            name=entry.card.name,
            versions=["1"],
            platform="dynamo_tpu",
            inputs=[pb.ModelMetadataResponse.TensorMetadata(
                name="text_input", datatype="BYTES", shape=[1])],
            outputs=[pb.ModelMetadataResponse.TensorMetadata(
                name="text_output", datatype="BYTES", shape=[1])],
        )

    @staticmethod
    def _start_trace(preprocessed, context, span_name_is_stream: bool,
                     received: Optional[float] = None):
        """SERVER span + flight-recorder timeline for one gRPC inference —
        the same observability contract as the HTTP path (previously the
        kserve surface only logged the traceparent)."""
        tp = _grpc_traceparent(context)
        span = get_tracer().start_span(
            "grpc.stream_infer" if span_name_is_stream else "grpc.infer",
            parent=tp, kind=2,
            **{"request.id": preprocessed.request_id,
               "model": preprocessed.model,
               "input.tokens": len(preprocessed.token_ids)})
        wire_tp = span.traceparent or tp
        if wire_tp:
            preprocessed.annotations["traceparent"] = wire_tp
        current_request_id.set(preprocessed.request_id)
        current_trace_id.set(trace_id_of(wire_tp) or None)
        # Record the trace id of the traceparent actually forwarded on
        # the wire — same semantics as the HTTP path, which keeps the
        # client's trace id even when local export is disabled.
        get_recorder().start(preprocessed.request_id,
                             model=preprocessed.model,
                             trace_id=trace_id_of(wire_tp),
                             tenant=preprocessed.tenant,
                             received=received)
        return span

    async def _model_infer(self, request, context) -> pb.ModelInferResponse:
        arrival = time.time()
        entry, preprocessed = await self._preprocess(request, context)
        delta_gen = DeltaGenerator(entry.preprocessor, preprocessed,
                                   kind="completions")
        span = self._start_trace(preprocessed, context,
                                 span_name_is_stream=False,
                                 received=arrival)
        status = "error"
        try:
            async for output in entry.engine.generate(preprocessed):
                delta_gen.on_output(output)
                if output.error:
                    # abort raises; the span closes ok=False below.
                    await context.abort(grpc.StatusCode.INTERNAL,
                                        output.error)
            status = "ok"
            span.end(ok=True)
            return _text_response(request.model_name, request.id,
                                  delta_gen.full_text)
        except asyncio.CancelledError:
            # Client cancelled the RPC: routine teardown, not an error —
            # same classification as the HTTP path (keeps the flight
            # recorder from WARNING-dumping every normal cancel).
            status = "cancelled"
            raise
        finally:
            # Aborts, client cancellation, and engine exceptions all pass
            # here: the span must never leak open (first end() wins).
            span.end(ok=False)
            get_recorder().finish(preprocessed.request_id, status)

    async def _model_stream_infer(
        self, request_iterator, context
    ) -> AsyncIterator[pb.ModelStreamInferResponse]:
        async for request in request_iterator:
            arrival = time.time()
            entry, preprocessed = await self._preprocess(request, context)
            delta_gen = DeltaGenerator(entry.preprocessor, preprocessed,
                                       kind="completions")
            span = self._start_trace(preprocessed, context,
                                     span_name_is_stream=True,
                                     received=arrival)
            status = "error"
            try:
                async for output in entry.engine.generate(preprocessed):
                    for chunk in delta_gen.on_output(output):
                        text = chunk["choices"][0].get("text", "")
                        if text:
                            yield pb.ModelStreamInferResponse(
                                infer_response=_text_response(
                                    request.model_name, request.id, text))
                    if delta_gen.finish_reason is not None:
                        break
                # Terminal empty response carrying the finish marker.
                final = _text_response(request.model_name, request.id, "")
                final.parameters["triton_final_response"].bool_param = True
                yield pb.ModelStreamInferResponse(infer_response=final)
                status = "ok"
                span.end(ok=True)
            except asyncio.CancelledError:
                # Client cancelled the stream: routine teardown, not an
                # error (suppresses the recorder's WARNING auto-dump).
                status = "cancelled"
                raise
            except GeneratorExit:
                # grpc.aio aclose()d the handler generator (stream torn
                # down without task cancellation): same routine teardown.
                status = "cancelled"
                raise
            except Exception as exc:  # noqa: BLE001 — deliver as stream error
                yield pb.ModelStreamInferResponse(error_message=str(exc))
            finally:
                # Stream torn down mid-request (client cancel) included.
                span.end(ok=False)
                get_recorder().finish(preprocessed.request_id, status)

    # -- lifecycle ---------------------------------------------------------

    def _handlers(self) -> grpc.GenericRpcHandler:
        def unary(fn, req_cls, resp_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=resp_cls.SerializeToString)

        method_handlers = {
            "ServerLive": unary(self._server_live, pb.ServerLiveRequest,
                                pb.ServerLiveResponse),
            "ServerReady": unary(self._server_ready, pb.ServerReadyRequest,
                                 pb.ServerReadyResponse),
            "ModelReady": unary(self._model_ready, pb.ModelReadyRequest,
                                pb.ModelReadyResponse),
            "ServerMetadata": unary(self._server_metadata,
                                    pb.ServerMetadataRequest,
                                    pb.ServerMetadataResponse),
            "ModelMetadata": unary(self._model_metadata,
                                   pb.ModelMetadataRequest,
                                   pb.ModelMetadataResponse),
            "ModelInfer": unary(self._model_infer, pb.ModelInferRequest,
                                pb.ModelInferResponse),
            "ModelStreamInfer": grpc.stream_stream_rpc_method_handler(
                self._model_stream_infer,
                request_deserializer=pb.ModelInferRequest.FromString,
                response_serializer=pb.ModelStreamInferResponse.SerializeToString),
        }
        return grpc.method_handlers_generic_handler(_SERVICE, method_handlers)

    async def start(self) -> None:
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        await self._server.start()
        log.info("KServe gRPC frontend listening on %s:%d", self.host,
                 self.port)

    async def close(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=2.0)
            self._server = None


