"""ModelManager + ModelWatcher: discovery-driven serving pipelines.

The frontend does not get configured with workers — it watches discovery for
ModelDeploymentCards and (re)builds a serving pipeline per model as worker
instances come and go (ref: lib/llm/src/discovery/watcher.rs:68 ModelWatcher,
model_manager.rs:67 ModelManager; flow in section 3.1). When the last
instance of a model disappears, the model is unlisted.
"""

from __future__ import annotations

import asyncio
import dataclasses
import threading
from typing import Optional

from ..kv_router import (
    KV_EVENT_TOPIC,
    KV_SNAPSHOT_TOPIC,
    LOAD_TOPIC,
    KvRouterConfig,
    KvScheduler,
    LoadMetrics,
    RouterEvent,
    WorkerWithDpRank,
)
from ..runtime.admission import QueueWaitEstimator
from ..runtime.config import env
from ..runtime.discovery import MODEL_CARD_PREFIX
from ..runtime.events import JOURNAL_RESYNC_TOPIC
from ..session import SESSION_PIN_TOPIC
from ..runtime.logging import get_logger
from ..runtime.push_router import PushRouter
from .engine import (
    KvRouterEngine,
    Migration,
    MultimodalEngine,
    RouterEngine,
    TokenEngine,
)
from .model_card import (
    CHAT,
    COMPLETIONS,
    ENCODER,
    IMAGE,
    PREFILL,
    ModelDeploymentCard,
)
from .prefill_router import PrefillPool, PrefillRouterEngine
from .preprocessor import OpenAIPreprocessor

log = get_logger("llm.manager")


@dataclasses.dataclass
class ModelEntry:
    card: ModelDeploymentCard
    preprocessor: OpenAIPreprocessor
    engine: TokenEngine
    router: PushRouter
    scheduler: Optional[KvScheduler]
    instances: set[int] = dataclasses.field(default_factory=set)
    # worker instance_id -> last published kv usage (any router mode; feeds
    # busy-threshold load shedding)
    worker_usage: dict[int, float] = dataclasses.field(default_factory=dict)
    # worker instance_id -> adapters it advertises (cards republish on LoRA
    # load/unload); the model's adapter set is the UNION — per-instance
    # eligibility is enforced at routing time via lora_instances.
    instance_loras: dict[int, list[str]] = dataclasses.field(
        default_factory=dict)
    # Deadline-aware admission (runtime/admission.py): queue-wait estimate
    # for this model's serving pool — depth from worker-published
    # waiting_requests (LoadMetrics), drain rate from the frontend's own
    # first-token stream.
    wait_estimator: QueueWaitEstimator = dataclasses.field(
        default_factory=QueueWaitEstimator)
    # Session/prompt-cache tier (dynamo_tpu/session): pin leases +
    # session affinity for this model. None when DYNT_SESSION_ENABLE=0.
    session: Optional[object] = None
    # Graceful drain plane (docs/fault-tolerance.md): instances that
    # flipped to draining (LoadMetrics.draining or the card flag). Their
    # radix state is decayed once and further KV events from them are
    # skipped — a vacating worker's prefixes must not keep attracting
    # overlap routing while it hands its sequences off.
    draining: set = dataclasses.field(default_factory=set)

    def __post_init__(self) -> None:
        self.wait_estimator.pool = f"decode:{self.card.name}"

    def loras(self) -> set[str]:
        return {name for ls in self.instance_loras.values() for name in ls}

    def lora_instances(self, name: str) -> set[int]:
        return {iid for iid, ls in self.instance_loras.items() if name in ls}


class ModelManager:
    """model name -> serving pipeline registry."""

    def __init__(self) -> None:
        self._models: dict[str, ModelEntry] = {}
        # Diffusion pools (model type `image`): served by their own worker
        # kind; the HTTP /v1/images/generations + /v1/videos routes call
        # these directly (maintained by the ModelWatcher).
        self.image_pools: dict[str, PrefillPool] = {}
        # register/unregister run from the discovery watcher while resolve/
        # list_models serve concurrent HTTP handlers and scheduler hooks;
        # iteration during mutation raises RuntimeError on dicts, so every
        # touch takes the lock (registry ops are tiny — never contended).
        self._lock = threading.Lock()

    def register(self, entry: ModelEntry) -> None:
        with self._lock:
            self._models[entry.card.name] = entry

    def unregister(self, name: str) -> None:
        with self._lock:
            self._models.pop(name, None)

    def get(self, name: str) -> Optional[ModelEntry]:
        with self._lock:
            return self._models.get(name)

    def resolve(self, name: str) -> tuple[Optional[ModelEntry], Optional[str]]:
        """Resolve a requested model name to (entry, lora_name). A name
        matching a LoRA adapter advertised in some model's card routes to
        that base model with the adapter applied (ref: lora.rs — adapters
        are served as model names)."""
        with self._lock:
            entry = self._models.get(name)
            if entry is not None:
                return entry, None
            for entry in self._models.values():
                if name in entry.loras():
                    return entry, name
        return None, None

    def list_models(self) -> list[ModelDeploymentCard]:
        with self._lock:
            return [e.card for e in self._models.values()]

    def list_adapters(self) -> list[tuple[str, str]]:
        """(adapter_name, base_model_name) pairs across all entries."""
        out = []
        with self._lock:
            entries = list(self._models.values())
        for entry in entries:
            for name in sorted(entry.loras()):
                out.append((name, entry.card.name))
        return out

    def entries(self) -> list[ModelEntry]:
        with self._lock:
            return list(self._models.values())


class ModelWatcher:
    """Watches v1/mdc/ and maintains the ModelManager (ref: watcher.rs
    handle_put/handle_delete)."""

    def __init__(
        self,
        runtime,
        manager: ModelManager,
        router_mode: str = "round_robin",
        kv_config: Optional[KvRouterConfig] = None,
        namespace_filter: Optional[str] = None,
    ) -> None:
        self.runtime = runtime
        self.manager = manager
        self.router_mode = router_mode
        self.kv_config = kv_config
        # Only track cards in this namespace (the global router runs one
        # watcher per pool namespace; a frontend watches everything).
        self.namespace_filter = namespace_filter
        self._watch = None
        self._tasks: list[asyncio.Task] = []
        self._maintain_task: Optional[asyncio.Task] = None
        # model name -> prefill worker pool (disagg; ref prefill_router/
        # activation.rs — the PrefillRouterEngine activates when a pool has
        # live instances). _prefill_subjects maps endpoint subject -> name
        # so lease-expiry deletes drain the right pool.
        self._prefill_pools: dict[str, PrefillPool] = {}
        self._prefill_subjects: dict[str, str] = {}
        # Multimodal encoder pools (same shape as prefill pools): model
        # name -> pool of encode workers the MultimodalEngine calls.
        self._encoder_pools: dict[str, PrefillPool] = {}
        self._encoder_subjects: dict[str, str] = {}
        self._image_subjects: dict[str, str] = {}
        # (subject, worker_id) -> events buffered while a resync RPC is in
        # flight for that worker; replayed (ids beyond the dump) after the
        # snapshot loads — the classic snapshot+replay pattern, so live
        # traffic during the RPC window can neither be lost nor re-applied.
        self._resyncing: dict = {}
        # namespace -> entries fed by that namespace's event stream; the
        # list is shared with the running _event_loop so late-registered
        # models start receiving events immediately.
        self._ns_entries: dict[str, list[ModelEntry]] = {}
        # namespace -> event publisher for session pin reconciliation
        # (created lazily by the maintain loop's first drain).
        self._session_publishers: dict = {}

    async def start(self) -> None:
        self._watch = await self.runtime.discovery.watch_prefix(
            MODEL_CARD_PREFIX + "/"
        )
        self._tasks.append(asyncio.create_task(self._watch_loop()))

    async def close(self) -> None:
        for task in self._tasks:
            task.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        if self._watch is not None:
            await self._watch.cancel()
        for entry in self.manager.entries():
            await entry.router.client.close()
        for pool in self._prefill_pools.values():
            await pool.router.client.close()
        for pool in self._encoder_pools.values():
            await pool.router.client.close()
        for pool in self.manager.image_pools.values():
            await pool.router.client.close()
        for publisher in self._session_publishers.values():
            try:
                await publisher.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                log.exception("session publisher close failed")

    async def _watch_loop(self) -> None:
        async for event in self._watch:
            try:
                if event.kind == "put" and event.value:
                    await self._handle_put(event.key, event.value)
                elif event.kind == "delete":
                    await self._handle_delete(event.key)
            except Exception:  # noqa: BLE001 — watcher must survive bad cards
                log.exception("model watcher failed handling %s", event.key)

    @staticmethod
    def _parse_key(key: str) -> tuple[str, int]:
        # v1/mdc/{ns}/{component}/{endpoint}/{instance_id}
        parts = key.split("/")
        return "/".join(parts[2:5]), int(parts[5])

    async def _handle_put(self, key: str, value: dict) -> None:
        subject, instance_id = self._parse_key(key)
        if (self.namespace_filter is not None
                and subject.split("/", 1)[0] != self.namespace_filter):
            return
        card = ModelDeploymentCard.from_wire(value)
        if IMAGE in card.model_types:
            await self._pool_put(card, subject, instance_id,
                                 self.manager.image_pools,
                                 self._image_subjects, "image")
            return
        if ENCODER in card.model_types:
            await self._handle_encoder_put(card, subject, instance_id)
            return
        if PREFILL in card.model_types:
            await self._handle_prefill_put(card, subject, instance_id)
            if not ({CHAT, COMPLETIONS} & set(card.model_types)):
                return
            # Dual-role card (e.g. the global router registers as BOTH a
            # prefill and a chat model, ref: global_router/README): fall
            # through to normal model registration too.
        entry = self.manager.get(card.name)
        if entry is None:
            entry = self._build_entry(card)
            await entry.router.client.start()
            self.manager.register(entry)
            await self._subscribe_events(card.namespace, entry)
            log.info("model registered: %s (%s, router=%s)", card.name,
                     subject, self.router_mode)
        elif entry.card.endpoint_subject != subject:
            # Same model name served from a different endpoint: first one
            # wins (instance bookkeeping must stay per-subject or deletes
            # can never drain the entry).
            log.warning(
                "model %s already served at %s; ignoring instance at %s",
                card.name, entry.card.endpoint_subject, subject)
            return
        newly_seen = instance_id not in entry.instances
        entry.instances.add(instance_id)
        # Per-instance adapter list (cards republish on LoRA load/unload);
        # never overwrite the entry card wholesale — with multiple instances
        # the last publisher would clobber the others' state.
        entry.instance_loras[instance_id] = list(
            card.runtime_config.get("loras", []))
        if card.runtime_config.get("draining"):
            # The worker announced its departure on the discovery plane
            # (engine/drain.py announce): stop selecting it, decay its
            # radix state, and skip the bootstrap resync — dumping a
            # vacating worker's index would re-attract traffic to it.
            self._mark_draining(entry, instance_id)
            return
        if (newly_seen and entry.scheduler is not None
                and card.runtime_config.get("kv_blocks_endpoint")):
            # Bootstrap this worker's radix state from its local indexer
            # (ref: router-design.md — "on worker discovery it dumps full
            # state"; this is also what lets a RESTARTED router recover
            # routing state without a durable event log). Gated on the card
            # advertising the kv_blocks endpoint — proxies like the global
            # router don't serve one.
            self._schedule_resync(entry, instance_id, reason="discovered")

    async def _pool_put(self, card: ModelDeploymentCard, subject: str,
                        instance_id: int, pools: dict, subjects: dict,
                        label: str) -> None:
        """Shared worker-pool lifecycle (prefill / encoder / image pools):
        one pool per model name, bound to the FIRST endpoint subject seen —
        a second subject's instances are ignored (the pool's router can't
        reach them and deletes could never drain them, mirroring the
        decode-entry guard above)."""
        pool = pools.get(card.name)
        if pool is not None and subjects.get(subject) != card.name:
            log.warning("%s pool for %s already bound to another subject; "
                        "ignoring instance at %s", label, card.name, subject)
            return
        if pool is None:
            endpoint = (
                self.runtime.namespace(card.namespace)
                .component(card.component)
                .endpoint(card.endpoint)
            )
            pool = PrefillPool(router=PushRouter(endpoint.client(),
                                                 mode="round_robin"))
            await pool.router.client.start()
            pools[card.name] = pool
            subjects[subject] = card.name
            log.info("%s pool up for %s (%s)", label, card.name, subject)
        pool.instances.add(instance_id)
        if card.runtime_config.get("draining"):
            # Departure announce on the discovery plane (engine/drain.py):
            # a vacating pool worker must stop attracting new legs.
            if pool.router.set_draining(instance_id, True):
                estimator = getattr(pool, "wait_estimator", None)
                if estimator is not None:
                    estimator.update_worker(instance_id, 0)
                log.info("%s pool worker %x draining for %s", label,
                         instance_id, card.name)

    async def _handle_prefill_put(self, card, subject, instance_id) -> None:
        await self._pool_put(card, subject, instance_id,
                             self._prefill_pools, self._prefill_subjects,
                             "prefill")

    async def _handle_encoder_put(self, card, subject, instance_id) -> None:
        await self._pool_put(card, subject, instance_id,
                             self._encoder_pools, self._encoder_subjects,
                             "encoder")

    async def _handle_delete(self, key: str) -> None:
        subject, instance_id = self._parse_key(key)
        if (self.namespace_filter is not None
                and subject.split("/", 1)[0] != self.namespace_filter):
            return
        for pools, subjects, label in (
                (self.manager.image_pools, self._image_subjects, "image"),
                (self._encoder_pools, self._encoder_subjects, "encoder"),
                (self._prefill_pools, self._prefill_subjects, "prefill"),
        ):
            name = subjects.get(subject)
            if name is None:
                continue
            pool = pools.get(name)
            if pool is not None:
                pool.instances.discard(instance_id)
                if not pool.instances:
                    log.info("%s pool drained for %s", label, name)
                    pools.pop(name, None)
                    subjects.pop(subject, None)
                    await pool.router.client.close()
            if label != "prefill":
                return
            # prefill: NO return — a dual-role card's subject may ALSO back
            # a chat entry (global router); fall through and drain it too.
            break
        for entry in self.manager.entries():
            if entry.card.endpoint_subject == subject:
                entry.instances.discard(instance_id)
                entry.instance_loras.pop(instance_id, None)
                # Deregistration completes a drain: clear the mark so a
                # RESTARTED worker at the same id starts clean (the
                # router's own _draining set clears on the same delete).
                entry.draining.discard(instance_id)
                if entry.scheduler is not None:
                    entry.scheduler.remove_worker_id(instance_id)
                # Session residency is invalidated LAZILY: a departed
                # worker is simply absent from the router's candidate
                # set, so its affinity bonus no-ops; the next routed
                # turn overwrites the entry. An eager
                # store.remove_worker_id scan would walk up to
                # DYNT_SESSION_MAX entries on the event loop per
                # departure. Pins survive either way — the KV may still
                # be tiered and another worker can onboard it.
                if not entry.instances:
                    log.info("model unlisted: %s (last instance gone)",
                             entry.card.name)
                    self.manager.unregister(entry.card.name)
                    entries = self._ns_entries.get(entry.card.namespace, [])
                    if entry in entries:
                        entries.remove(entry)
                    await entry.router.client.close()

    def _mark_draining(self, entry: ModelEntry, instance_id: int) -> None:
        """One-shot draining transition for a decode instance: exclude
        it from routing (PushRouter.available), decay its radix state so
        overlap scoring stops preferring it, zero its admission-depth
        contribution, and skip its future KV events. Runs from both the
        LoadMetrics path and the card-flag path; set_draining dedups."""
        if not entry.router.set_draining(instance_id, True):
            return
        entry.draining.add(instance_id)
        if entry.scheduler is not None:
            entry.scheduler.remove_worker_id(instance_id)
        # Its backlog is migrating out, not queue depth new arrivals
        # wait behind.
        entry.wait_estimator.update_worker(instance_id, 0)
        log.info("worker %x draining: removed from selection for %s",
                 instance_id, entry.card.name)

    # -- worker state resync (bootstrap + gap recovery) --------------------

    def _schedule_resync(self, entry: ModelEntry, instance_id: int,
                         reason: str) -> None:
        if instance_id in entry.draining:
            # Vacating worker (docs/fault-tolerance.md departure ladder):
            # _mark_draining decayed its radix state on purpose —
            # re-dumping its index would re-attract overlap routing to a
            # worker that is handing its sequences off, and its
            # endpoints are shutting down anyway. Central guard: covers
            # the gap, journal-corrupt, and bootstrap paths.
            return
        key = (entry.card.endpoint_subject, instance_id)
        if key in self._resyncing:
            return
        self._resyncing[key] = []  # event buffer; _event_loop fills it
        task = asyncio.create_task(
            self._resync_worker(entry, instance_id, reason, key))
        self._tasks.append(task)
        task.add_done_callback(
            lambda t: self._tasks.remove(t) if t in self._tasks else None)

    async def _resync_worker(self, entry: ModelEntry, instance_id: int,
                             reason: str, key) -> None:
        card = entry.card
        client = (
            self.runtime.namespace(card.namespace)
            .component(card.component)
            .endpoint("kv_blocks")
            .client()
        )
        regap = False
        try:
            await client.start()
            await client.wait_for_instances(1, timeout=10)
            async for dump in client.direct({}, instance_id):
                worker = WorkerWithDpRank(dump["worker_id"],
                                          dump.get("dp_rank", 0))
                pairs = [(p, h) for p, h in dump.get("blocks", [])]
                dump_last = dump.get("last_event_id")
                entry.scheduler.indexer.load_worker(worker, pairs, dump_last)
                # Replay events that arrived during the RPC. Anything the
                # dump already reflects (id <= dump_last) is skipped by the
                # indexer's stale check; newer ones apply in order. No await
                # between pop and replay, so no event can slip past both.
                buffered = self._resyncing.pop(key, [])
                for event in buffered:
                    if entry.scheduler.indexer.apply_event(event) == "gap":
                        regap = True
                log.info("resynced worker %x for %s (%s): %d blocks, "
                         "%d events replayed", instance_id, card.name,
                         reason, len(pairs), len(buffered))
                break
        except Exception:  # noqa: BLE001 — resync is best-effort; events
            # keep flowing and a later gap retries
            log.exception("kv resync failed for %x (%s)", instance_id, reason)
        finally:
            # Failure path: don't drop what was buffered — apply it (the
            # first event will re-flag a gap on the next live event if the
            # stream is still inconsistent). Success path already popped.
            for event in self._resyncing.pop(key, []):
                try:
                    entry.scheduler.indexer.apply_event(event)
                except Exception:  # noqa: BLE001
                    log.exception("buffered event replay failed")
            await client.close()
        if regap:
            # An event was lost inside the resync window itself — without
            # this, _last_event_id has advanced and the live path would
            # never notice. Scheduled strictly AFTER the finally above so
            # the retry's fresh buffer can't be popped by this invocation.
            self._schedule_resync(entry, instance_id, reason="replay-gap")

    def _build_entry(self, card: ModelDeploymentCard) -> ModelEntry:
        endpoint = (
            self.runtime.namespace(card.namespace)
            .component(card.component)
            .endpoint(card.endpoint)
        )
        client = endpoint.client()
        scheduler: Optional[KvScheduler] = None
        # Shared with the ModelEntry below: routing reads live per-instance
        # adapter state maintained by the watcher.
        instance_loras: dict[int, list[str]] = {}

        def lora_lookup(adapter: str) -> set[int]:
            return {iid for iid, ls in instance_loras.items() if adapter in ls}

        session = None
        if env("DYNT_SESSION_ENABLE"):
            from ..session import SessionTier

            session = SessionTier(card.name, card.kv_block_size)
        if self.router_mode == "kv":
            config = self.kv_config or KvRouterConfig()
            config = dataclasses.replace(config, block_size=card.kv_block_size)
            scheduler = KvScheduler(config)
            router = PushRouter(client, mode="round_robin")
            engine: TokenEngine = KvRouterEngine(router, scheduler,
                                                 lora_instances=lora_lookup,
                                                 session=session)
        else:
            router = PushRouter(client, mode=self.router_mode)
            engine = RouterEngine(router, lora_instances=lora_lookup)
        name = card.name
        engine = PrefillRouterEngine(
            engine, pool_lookup=lambda: self._prefill_pools.get(name)
        )
        engine = Migration(engine, migration_limit=env("DYNT_MIGRATION_LIMIT"))
        # Outermost: images are encoded ONCE, before any migration retry
        # re-dispatch (embeddings travel with the replayed request).
        engine = MultimodalEngine(
            engine, pool_lookup=lambda: self._encoder_pools.get(name)
        )
        preprocessor = OpenAIPreprocessor(card)
        return ModelEntry(
            card=card,
            preprocessor=preprocessor,
            engine=engine,
            router=router,
            scheduler=scheduler,
            instances=set(),
            instance_loras=instance_loras,
            session=session,
        )

    async def _subscribe_events(self, namespace: str, entry: ModelEntry) -> None:
        """Feed KV events + load metrics from the event plane into every
        model entry in this namespace (ref: kv_router/subscriber.rs; section
        3.3 feedback path). Load metrics flow in every router mode (they
        drive busy-threshold shedding); KV events only matter to entries
        with a scheduler."""
        entries = self._ns_entries.get(namespace)
        if entries is not None:
            entries.append(entry)
            return
        entries = [entry]
        self._ns_entries[namespace] = entries
        sub = await self.runtime.event_subscriber(namespace, topic_prefix="")
        self._tasks.append(asyncio.create_task(self._event_loop(sub, entries)))
        if self._maintain_task is None:
            self._maintain_task = asyncio.create_task(
                self._indexer_maintain_loop())
            self._tasks.append(self._maintain_task)

    async def _indexer_maintain_loop(self, interval: float = 1.0) -> None:
        """Radix-index TTL/size sweep for every KV-routed entry (no-op
        unless DYNT_INDEXER_TTL_SECS/_MAX_TREE_SIZE enable pruning;
        ref: indexer/pruning.rs driven from the indexer loop), plus the
        session tier's lease/store sweep and pin-event publication —
        the reconciliation feed peer router replicas converge on."""
        from ..kv_router.indexer import sweep_tree

        while True:
            await asyncio.sleep(interval)
            for namespace, entries in self._ns_entries.items():
                for entry in entries:
                    if entry.scheduler is not None:
                        sweep_tree(entry.scheduler.indexer,
                                   entry.card.name, log)
                    if entry.session is not None:
                        try:
                            entry.session.sweep()
                            await self._publish_session_events(namespace,
                                                               entry)
                        except Exception:  # noqa: BLE001 — the sweep
                            # loop must survive a publisher hiccup
                            log.exception("session sweep/publish failed "
                                          "(%s)", entry.card.name)

    async def _publish_session_events(self, namespace: str, entry) -> None:
        events = entry.session.drain_events()
        if not events:
            return
        publisher = self._session_publishers.get(namespace)
        if publisher is None:
            publisher = self.runtime.event_publisher(namespace)
            if hasattr(publisher, "advertise"):
                await publisher.advertise()
            self._session_publishers[namespace] = publisher
        for i, payload in enumerate(events):
            try:
                await publisher.publish(SESSION_PIN_TOPIC, payload)
            except Exception:
                # A publisher hiccup must not lose the drained tail —
                # requeue it (front, original order) for the next tick
                # or peer replicas silently diverge until lease TTL.
                for p in reversed(events[i:]):
                    entry.session.outbox.appendleft(p)
                raise

    async def _event_loop(self, sub, entries: list[ModelEntry]) -> None:
        async for topic, payload in sub:
            try:
                if topic.startswith(KV_EVENT_TOPIC):
                    event = RouterEvent.from_wire(payload)
                    for entry in entries:
                        if entry.scheduler is None:
                            continue
                        if event.worker_id in entry.draining:
                            # The worker is vacating: applying its late
                            # KV events would re-create the radix state
                            # _mark_draining just decayed (and a gap
                            # verdict would resync it right back in).
                            continue
                        key = (entry.card.endpoint_subject, event.worker_id)
                        buffer = self._resyncing.get(key)
                        if buffer is not None:
                            # Resync in flight: hold this worker's events
                            # for replay after the snapshot loads.
                            buffer.append(event)
                            continue
                        status = entry.scheduler.indexer.apply_event(event)
                        if (status == "gap"
                                and event.worker_id in entry.instances
                                and entry.card.runtime_config.get(
                                    "kv_blocks_endpoint")):
                            # Missed events: replace this worker's view
                            # from its local indexer (ref: worker_query).
                            self._schedule_resync(entry, event.worker_id,
                                                  reason="gap")
                elif topic.startswith(KV_SNAPSHOT_TOPIC):
                    # Journal rotation snapshot: replace that worker's view
                    # wholesale (same application path as worker resync).
                    worker = WorkerWithDpRank(payload["worker_id"],
                                              payload.get("dp_rank", 0))
                    for entry in entries:
                        if entry.scheduler is None:
                            continue
                        if payload["worker_id"] in entry.draining:
                            continue  # vacating: stay decayed
                        key = (entry.card.endpoint_subject,
                               payload["worker_id"])
                        if key in self._resyncing:
                            continue  # live resync wins; it is fresher
                        entry.scheduler.indexer.load_worker(
                            worker,
                            [(p, h) for p, h in payload.get("blocks", [])],
                            payload.get("last_event_id"))
                elif topic.startswith(JOURNAL_RESYNC_TOPIC):
                    # The durable journal skipped corrupt frames: KV
                    # events were lost with no per-worker gap to flag
                    # them, so re-dump EVERY routed worker's state from
                    # its local indexer (the dump_worker/load_worker
                    # round-trip) instead of silently diverging from
                    # peer replicas. _schedule_resync dedups in-flight
                    # keys, so a burst of skips costs one RPC per worker.
                    for entry in entries:
                        if entry.scheduler is None or not \
                                entry.card.runtime_config.get(
                                    "kv_blocks_endpoint"):
                            continue
                        for iid in list(entry.instances):
                            self._schedule_resync(entry, iid,
                                                  reason="journal-corrupt")
                elif topic.startswith(SESSION_PIN_TOPIC):
                    # Peer router replica's pin/route/touch: apply so
                    # both replicas converge on the same pin set +
                    # residency map (self-echoes filtered by origin id).
                    for entry in entries:
                        if entry.session is not None:
                            entry.session.apply_event(payload)
                elif topic.startswith(LOAD_TOPIC):
                    metrics = LoadMetrics.from_wire(payload)
                    for entry in entries:
                        entry.worker_usage[metrics.worker_id] = metrics.kv_usage
                        if metrics.draining \
                                and metrics.worker_id in entry.instances:
                            # Departure announce via the load plane
                            # (engine/drain.py): faster than waiting for
                            # the card republish to land. Skip the usual
                            # bookkeeping below — update_published would
                            # re-add the worker remove_worker_id just
                            # dropped, and its backlog is migrating out,
                            # not queue depth new arrivals wait behind.
                            self._mark_draining(entry, metrics.worker_id)
                            continue
                        if entry.scheduler is not None:
                            entry.scheduler.sequences.update_published(metrics)
                        if metrics.worker_id in entry.instances:
                            # Deadline-aware admission depth signal: the
                            # scheduler's own step-loop queue stats
                            # (waiting_requests) per live decode worker.
                            entry.wait_estimator.update_worker(
                                metrics.worker_id, metrics.waiting_requests)
                    for pool in self._prefill_pools.values():
                        if metrics.worker_id in pool.instances:
                            if metrics.draining:
                                # Draining prefill worker: stop selecting
                                # it for new legs; in-flight transfers
                                # its decode peers are pulling finish on
                                # their own (the drain deadline bounds
                                # them).
                                if pool.router.set_draining(
                                        metrics.worker_id, True):
                                    pool.wait_estimator.update_worker(
                                        metrics.worker_id, 0)
                                continue
                            pool.wait_estimator.update_worker(
                                metrics.worker_id, metrics.waiting_requests)
            except Exception:  # noqa: BLE001
                log.exception("bad event on %s", topic)
