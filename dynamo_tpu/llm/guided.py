"""Guided decoding: constrained generation for structured outputs.

The reference protocol carries per-request guided-decoding options —
`guided_decoding: {json | regex | choice | grammar}` in
`lib/llm/src/protocols/common.rs:339-361` and OpenAI `response_format`
json_object/json_schema — and delegates enforcement to its engines
(vLLM/TRT-LLM ship xgrammar/outlines-class backends). We own the
engine, so the constraint engine lives here:

  pattern --parse--> NFA (Thompson, byte alphabet) --subset--> DFA
  (eager, over byte-class partitions) --> TokenGuide (per-DFA-state
  allowed-token masks, computed lazily per state by walking every
  vocab token's UTF-8 bytes through the DFA in a few vectorized numpy
  steps) --> GuidedProcessor (a BaseLogitsProcessor: advance on each
  generated token, mask the next-token logits; EOS becomes legal
  exactly at accepting states).

Regex subset (enough for JSON-schema output grammars): literals,
escapes (\\d \\w \\s + their negations, control escapes), `.`
(any byte except newline), classes `[...]` with ranges and negation
(ASCII), groups `(...)`/`(?:...)`, alternation, and the quantifiers
`* + ? {m} {m,} {m,n}`. Patterns are anchored (fullmatch semantics),
matching the reference's guided-regex contract.

JSON support: `schema_to_regex` compiles a practical JSON-schema subset
(object properties in declaration order, string/enum/integer/number/
boolean/null, const, nested objects, arrays with minItems/maxItems) to
a near-compact grammar (single optional space after `:` and `,`);
`json_value_regex` is the generic JSON grammar expanded to a bounded
nesting depth (regular languages cannot count brackets — the classic
outlines-style approximation).
"""

from __future__ import annotations

import json
from typing import Any, Optional, Sequence

import numpy as np

# ---------------------------------------------------------------------------
# regex parsing -> AST


class _Pat:
    """AST nodes: ('char', byteset) | ('cat', [..]) | ('alt', [..]) |
    ('rep', node, min, max|None)."""


def _class_bytes(chars: str) -> np.ndarray:
    s = np.zeros(256, bool)
    for c in chars:
        s[ord(c)] = True
    return s


_DIGIT = _class_bytes("0123456789")
_WORD = _class_bytes(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = _class_bytes(" \t\n\r\f\v")
_ANY = np.ones(256, bool)
_ANY[ord("\n")] = False
_ESCAPE_SETS = {"d": _DIGIT, "D": ~_DIGIT, "w": _WORD, "W": ~_WORD,
                "s": _SPACE, "S": ~_SPACE}
_CTRL = {"n": "\n", "r": "\r", "t": "\t", "f": "\f", "v": "\v", "0": "\0"}


class RegexError(ValueError):
    pass


class _Parser:
    def __init__(self, pattern: str) -> None:
        self.p = pattern
        self.i = 0

    def _peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def _next(self) -> str:
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise RegexError(f"unexpected {self.p[self.i]!r} at "
                             f"{self.i} in {self.p!r}")
        return node

    def _alt(self):
        branches = [self._cat()]
        while self._peek() == "|":
            self._next()
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _cat(self):
        items = []
        while True:
            c = self._peek()
            if c is None or c in "|)":
                break
            items.append(self._quant())
        if not items:
            return ("cat", [])
        return items[0] if len(items) == 1 else ("cat", items)

    def _quant(self):
        node = self._atom()
        while True:
            c = self._peek()
            if c == "*":
                self._next()
                node = ("rep", node, 0, None)
            elif c == "+":
                self._next()
                node = ("rep", node, 1, None)
            elif c == "?":
                self._next()
                node = ("rep", node, 0, 1)
            elif c == "{":
                save = self.i
                self._next()
                spec = ""
                while self._peek() is not None and self._peek() != "}":
                    spec += self._next()
                if self._peek() != "}" or not _valid_brace(spec):
                    self.i = save  # literal '{'
                    break
                self._next()
                lo, hi = _parse_brace(spec)
                node = ("rep", node, lo, hi)
            else:
                break
        return node

    def _atom(self):
        c = self._next()
        if c == "(":
            if self.p[self.i:self.i + 2] == "?:":
                self.i += 2
            node = self._alt()
            if self._peek() != ")":
                raise RegexError("unbalanced '('")
            self._next()
            return node
        if c == "[":
            return ("char", self._cls())
        if c == ".":
            return ("char", _ANY.copy())
        if c == "\\":
            return self._escape()
        if c in "*+?":
            raise RegexError(f"dangling quantifier {c!r}")
        return _literal(c)

    def _hex_escape(self) -> str:
        if self.i + 1 >= len(self.p):
            raise RegexError("truncated \\x escape")
        hexs = self.p[self.i:self.i + 2]
        try:
            val = int(hexs, 16)
        except ValueError:
            raise RegexError(f"bad \\x escape {hexs!r}") from None
        self.i += 2
        return chr(val)

    def _escape(self):
        if self._peek() is None:
            raise RegexError("trailing backslash")
        c = self._next()
        if c in _ESCAPE_SETS:
            return ("char", _ESCAPE_SETS[c].copy())
        if c == "x":
            return _literal(self._hex_escape())
        if c in _CTRL:
            return _literal(_CTRL[c])
        return _literal(c)  # \" \\ \. \{ etc: the literal char

    def _cls(self):
        neg = False
        if self._peek() == "^":
            self._next()
            neg = True
        s = np.zeros(256, bool)
        first = True
        while True:
            c = self._peek()
            if c is None:
                raise RegexError("unbalanced '['")
            if c == "]" and not first:
                self._next()
                break
            first = False
            c = self._next()
            if c == "\\":
                e = self._next()
                if e in _ESCAPE_SETS:
                    s |= _ESCAPE_SETS[e]
                    continue
                c = self._hex_escape() if e == "x" else _CTRL.get(e, e)
            if self._peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self._next()
                hi = self._next()
                if hi == "\\":
                    e = self._next()
                    hi = self._hex_escape() if e == "x" \
                        else _CTRL.get(e, None)
                    if hi is None:
                        raise RegexError("bad range end escape")
                lo_b, hi_b = _char_byte(c), _char_byte(hi)
                if hi_b < lo_b:
                    raise RegexError(f"bad range {c}-{hi}")
                s[lo_b:hi_b + 1] = True
            else:
                b = c.encode("utf-8")
                if len(b) != 1:
                    raise RegexError(
                        "non-ASCII characters in classes are not "
                        "supported (use them as literals)")
                s[b[0]] = True
        return ~s if neg else s


def _char_byte(c: str) -> int:
    b = c.encode("utf-8")
    if len(b) != 1:
        raise RegexError("non-ASCII range bound")
    return b[0]


def _literal(c: str):
    bs = c.encode("utf-8")
    if len(bs) == 1:
        one = np.zeros(256, bool)
        one[bs[0]] = True
        return ("char", one)
    items = []
    for b in bs:  # multi-byte char: byte sequence
        one = np.zeros(256, bool)
        one[b] = True
        items.append(("char", one))
    return ("cat", items)


def _valid_brace(spec: str) -> bool:
    parts = spec.split(",")
    if len(parts) > 2 or not parts[0].isdigit():
        return False
    return len(parts) == 1 or parts[1] == "" or parts[1].isdigit()


def _parse_brace(spec: str):
    parts = spec.split(",")
    lo = int(parts[0])
    if len(parts) == 1:
        return lo, lo
    return lo, (int(parts[1]) if parts[1] else None)


# ---------------------------------------------------------------------------
# NFA (Thompson) -> DFA (subset construction over byte-class partitions)

_MAX_DFA_STATES = 20_000
_MAX_REP = 256  # {m,n} expansion cap — guards pathological patterns


def _build_nfa(node):
    """Returns (n_states, eps: list[set], trans: list[(byteset, dst)],
    start, accept). States are ints; trans[i] applies from state i."""
    eps: list[set] = []
    trans: list[list] = []

    def new_state() -> int:
        eps.append(set())
        trans.append([])
        return len(eps) - 1

    def build(n) -> tuple:
        kind = n[0]
        if kind == "char":
            s, e = new_state(), new_state()
            trans[s].append((n[1], e))
            return s, e
        if kind == "cat":
            if not n[1]:
                s = new_state()
                return s, s
            s, e = build(n[1][0])
            for item in n[1][1:]:
                s2, e2 = build(item)
                eps[e].add(s2)
                e = e2
            return s, e
        if kind == "alt":
            s, e = new_state(), new_state()
            for br in n[1]:
                bs, be = build(br)
                eps[s].add(bs)
                eps[be].add(e)
            return s, e
        if kind == "rep":
            _, inner, lo, hi = n
            if hi is not None and (hi > _MAX_REP or lo > _MAX_REP):
                raise RegexError(f"repetition bound > {_MAX_REP}")
            if lo > _MAX_REP:
                raise RegexError(f"repetition bound > {_MAX_REP}")
            s = new_state()
            e = s
            for _ in range(lo):
                s2, e2 = build(inner)
                eps[e].add(s2)
                e = e2
            if hi is None:
                s2, e2 = build(inner)
                eps[e].add(s2)
                eps[e2].add(s2)
                end = new_state()
                eps[e].add(end)
                eps[e2].add(end)
                return s, end
            ends = [e]
            for _ in range(hi - lo):
                s2, e2 = build(inner)
                eps[e].add(s2)
                e = e2
                ends.append(e)
            end = new_state()
            for x in ends:
                eps[x].add(end)
            return s, end
        raise RegexError(f"unknown node {kind}")

    start, accept = build(node)
    return eps, trans, start, accept


def compile_regex(pattern: str):
    """pattern -> Dfa (fullmatch semantics over UTF-8 bytes)."""
    node = _Parser(pattern).parse()
    eps, trans, start, accept = _build_nfa(node)

    n = len(eps)
    closure_cache: dict[int, frozenset] = {}

    def closure(states: frozenset) -> frozenset:
        out = set()
        stack = list(states)
        while stack:
            s = stack.pop()
            if s in out:
                continue
            out.add(s)
            stack.extend(eps[s] - out)
        return frozenset(out)

    # Byte partitions: group bytes by the signature of NFA transitions
    # that accept them — subset construction then runs over ~dozens of
    # classes instead of 256 bytes.
    all_sets = [bs for tlist in trans for (bs, _) in tlist]
    if all_sets:
        sig = np.zeros((256,), np.int64)
        mult = 1
        for bs in all_sets:
            sig = sig * 2 + bs.astype(np.int64)
            mult += 1
            if mult % 50 == 0:  # avoid int64 overflow: re-hash
                _, sig = np.unique(sig, return_inverse=True)
        _, class_of = np.unique(sig, return_inverse=True)
    else:
        class_of = np.zeros(256, np.int64)
    n_classes = int(class_of.max()) + 1
    class_rep = np.zeros(n_classes, np.int64)
    for cls in range(n_classes):
        class_rep[cls] = int(np.argmax(class_of == cls))

    start_set = closure(frozenset([start]))
    dfa_ids: dict[frozenset, int] = {start_set: 0}
    dfa_list = [start_set]
    table_cls: list[np.ndarray] = []
    i = 0
    while i < len(dfa_list):
        cur = dfa_list[i]
        row = np.full(n_classes, -1, np.int32)
        for cls in range(n_classes):
            byte = int(class_rep[cls])
            nxt = set()
            for s in cur:
                for bs, dst in trans[s]:
                    if bs[byte]:
                        nxt.add(dst)
            if nxt:
                closed = closure(frozenset(nxt))
                if closed not in dfa_ids:
                    if len(dfa_ids) >= _MAX_DFA_STATES:
                        raise RegexError(
                            "pattern compiles to too many DFA states")
                    dfa_ids[closed] = len(dfa_list)
                    dfa_list.append(closed)
                row[cls] = dfa_ids[closed]
        table_cls.append(row)
        i += 1

    table = np.stack(table_cls)[:, class_of]  # [n_dfa, 256]
    accepting = np.array([accept in s for s in dfa_list], bool)
    return Dfa(table, accepting)


class Dfa:
    """Dense byte DFA: table [n_states, 256] int32 (-1 = dead),
    accepting [n_states] bool. State 0 is the start."""

    def __init__(self, table: np.ndarray, accepting: np.ndarray) -> None:
        self.table = table
        self.accepting = accepting

    def fullmatch(self, data: bytes) -> bool:
        s = 0
        for b in data:
            s = int(self.table[s, b])
            if s < 0:
                return False
        return bool(self.accepting[s])


# ---------------------------------------------------------------------------
# token-level guide

class TokenGuide:
    """Per-DFA-state allowed-token masks over a tokenizer's vocab.

    Token byte walks are vectorized: all tokens advance one byte column
    at a time through the DFA table, so computing a new state's mask is
    O(max_token_len) numpy steps over [V]."""

    def __init__(self, dfa: Dfa, token_bytes: list[Optional[bytes]],
                 eos_ids: Sequence[int]) -> None:
        self.dfa = dfa
        self.eos_ids = [int(e) for e in eos_ids]
        v = len(token_bytes)
        lens = np.array([len(t) if t else 0 for t in token_bytes],
                        np.int32)
        lmax = max(1, int(lens.max()))
        padded = np.zeros((v, lmax), np.uint8)
        for i, t in enumerate(token_bytes):
            if t:
                padded[i, :len(t)] = np.frombuffer(t, np.uint8)
        self._padded = padded
        self._lens = lens
        # empty/special tokens can never advance a constraint
        self._eligible = lens > 0
        self._end_cache: dict[int, np.ndarray] = {}
        self._mask_cache: dict[int, np.ndarray] = {}

    def _end_states(self, state: int) -> np.ndarray:
        """[V] int32: DFA state after consuming each token from
        `state` (-1 = dead)."""
        out = self._end_cache.get(state)
        if out is None:
            v, lmax = self._padded.shape
            cur = np.full(v, state, np.int32)
            for col in range(lmax):
                active = (self._lens > col) & (cur >= 0)
                if not active.any():
                    break
                cur[active] = self.dfa.table[cur[active],
                                             self._padded[active, col]]
            cur[~self._eligible] = -1
            out = cur
            self._end_cache[state] = out
        return out

    def allowed(self, state: int) -> np.ndarray:
        """[V] bool: tokens that keep the constraint alive from
        `state` (EOS excluded — see `eos_allowed`)."""
        mask = self._mask_cache.get(state)
        if mask is None:
            mask = self._end_states(state) >= 0
            self._mask_cache[state] = mask
        return mask

    def eos_allowed(self, state: int) -> bool:
        return bool(self.dfa.accepting[state])

    def advance(self, state: int, token_id: int) -> int:
        if token_id in self.eos_ids:
            return state
        ends = self._end_states(state)
        if token_id >= len(ends):
            return -1
        return int(ends[token_id])


_TOKEN_BYTES_CACHE: dict[int, list] = {}


def _gpt2_byte_decoder() -> dict[str, int]:
    """Inverse of HF byte-level BPE's bytes_to_unicode: vocab char ->
    original byte. Byte-level vocabs spell every token with these 256
    characters (printable ASCII and Latin-1 map to themselves; the
    rest shift up past U+0100), so a token string whose chars ALL land
    in this table losslessly inverts to its true bytes."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


_BYTE_DECODER = _gpt2_byte_decoder()


def token_bytes_for(tokenizer) -> list[Optional[bytes]]:
    """Vocab id -> produced UTF-8 bytes (None for specials/unused).

    Byte-level-BPE vocabs (gpt2/llama3-style) are inverted through the
    raw token string and the bytes_to_unicode table — decode() yields
    U+FFFD for tokens carrying partial UTF-8 sequences (a multi-byte
    char split across tokens), which used to ban those tokens and make
    non-ASCII content ungeneratable under guided decoding. Cached per
    tokenizer: a 150k-vocab scan is seconds of decode calls and is
    identical for every pattern."""
    cached = _TOKEN_BYTES_CACHE.get(id(tokenizer))
    if cached is not None:
        return cached[1]
    out: list[Optional[bytes]] = []
    specials = getattr(tokenizer, "SPECIALS", {})
    token_text = getattr(tokenizer, "token_text", lambda i: None)
    raws = [token_text(i) for i in range(tokenizer.vocab_size)]
    # Vocab-level gate: byte-level-BPE vocabs (gpt2/llama3/qwen) spell
    # the space/newline bytes as Ġ (U+0120) / Ċ (U+010A) — present in
    # thousands of their tokens and in no other tokenizer family —
    # while SentencePiece vocabs carry the ▁ (U+2581) word marker
    # instead. Requiring Ġ/Ċ and rejecting on ▁ keeps non-byte-level
    # vocabs (SentencePiece '<0x0A>' byte fallback, WordPiece '##ing',
    # multilingual text tokens like 'ā' that happen to land in the
    # shifted alphabet) on the decode() path exactly as before.
    byte_level = any(
        raw and ("Ġ" in raw or "Ċ" in raw) for raw in raws
    ) and not any(raw and "▁" in raw for raw in raws)
    for i in range(tokenizer.vocab_size):
        if i in specials or i in getattr(tokenizer, "eos_token_ids", []):
            out.append(None)
            continue
        try:
            text = tokenizer.decode([i])
        except Exception:  # noqa: BLE001 — unused vocab slots
            out.append(None)
            continue
        raw = raws[i]
        # Empty decode = a special/added-control token the detokenizer
        # skips ('<|im_start|>' etc.) — it must stay banned even though
        # its raw spelling is plain ASCII; inverting it would let guided
        # patterns admitting '<' emit chat-control tokens the client
        # never sees.
        if byte_level and raw and text \
                and all(c in _BYTE_DECODER for c in raw):
            # byte-level BPE spelling: recover the true bytes, partial
            # UTF-8 sequences included (ASCII round-trips identically)
            out.append(bytes(_BYTE_DECODER[c] for c in raw))
            continue
        if not text or "�" in text:
            # partial UTF-8 pieces outside a byte-level vocab; byte
            # tokenizers expose raw bytes below 256 instead
            if hasattr(tokenizer, "SPECIALS") and i < 256:
                out.append(bytes([i]))
            else:
                out.append(None)
            continue
        out.append(text.encode("utf-8"))
    if len(_TOKEN_BYTES_CACHE) > 8:
        _TOKEN_BYTES_CACHE.clear()
    _TOKEN_BYTES_CACHE[id(tokenizer)] = (tokenizer, out)
    return out


# ---------------------------------------------------------------------------
# JSON grammars

_WS = " ?"  # near-compact: one optional space after ':' and ','
_STRING = r'"([^"\\\x00-\x1f]|\\["\\/bfnrt]|\\u[0-9a-fA-F]{4})*"'
_INTEGER = r"-?(0|[1-9][0-9]*)"
_NUMBER = r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?"
_BOOLEAN = r"(true|false)"
_NULL = r"null"


def _re_escape(text: str) -> str:
    out = []
    for c in text:
        if c in r"\.[]{}()*+?|^$/-":
            out.append("\\" + c)
        elif c == "\n":
            out.append(r"\n")
        elif c == "\t":
            out.append(r"\t")
        else:
            out.append(c)
    return "".join(out)


def _json_literal_regex(value: Any) -> str:
    return _re_escape(json.dumps(value, ensure_ascii=True))


def schema_to_regex(schema: dict, depth: int = 0) -> str:
    """JSON-schema subset -> output regex (see module docstring)."""
    if depth > 8:
        raise RegexError("schema nesting too deep (max 8)")
    if not isinstance(schema, dict):
        raise RegexError("schema must be an object")
    if "$ref" in schema or "$defs" in schema:
        raise RegexError("$ref/$defs are not supported")
    if "const" in schema:
        return _json_literal_regex(schema["const"])
    if "enum" in schema:
        opts = "|".join(_json_literal_regex(v) for v in schema["enum"])
        return f"({opts})"
    if "anyOf" in schema or "oneOf" in schema:
        subs = schema.get("anyOf") or schema.get("oneOf")
        return "(" + "|".join(schema_to_regex(s, depth + 1)
                              for s in subs) + ")"
    if not schema:
        # {} permits ANY JSON value (bounded nesting depth)
        return json_value_regex()
    typ = schema.get("type")
    if isinstance(typ, list):
        return "(" + "|".join(
            schema_to_regex({**schema, "type": t}, depth + 1)
            for t in typ) + ")"
    if typ == "string":
        return _STRING
    if typ == "integer":
        return _INTEGER
    if typ == "number":
        return _NUMBER
    if typ == "boolean":
        return _BOOLEAN
    if typ == "null":
        return _NULL
    if typ == "array":
        item = schema_to_regex(schema.get("items", {"type": "string"}),
                               depth + 1)
        lo = int(schema.get("minItems", 0))
        hi = schema.get("maxItems")
        more = f"(,{_WS}{item})"
        if hi is not None and int(hi) == 0:
            if lo > 0:
                raise RegexError("bad minItems/maxItems")
            return r"\[\]"  # maxItems 0: the array must be empty
        if hi is None:
            tail = f"{more}{{{max(lo - 1, 0)},}}" if lo > 1 else f"{more}*"
        else:
            hi = int(hi)
            if lo and hi < lo:
                raise RegexError("bad minItems/maxItems")
            tail = f"{more}{{{max(lo - 1, 0)},{hi - 1}}}"
        body = f"{item}{tail}"
        if lo == 0:
            body = f"({body})?"
        return rf"\[{body}\]"
    if typ == "object" or "properties" in schema:
        props = schema.get("properties") or {}
        if not props:
            # open object: any JSON object (bounded-depth values)
            return json_object_regex()
        parts = []
        for name, sub in props.items():
            key = _json_literal_regex(name)
            parts.append(f"{key}:{_WS}{schema_to_regex(sub, depth + 1)}")
        body = f",{_WS}".join(parts)
        return r"\{" + body + r"\}"
    raise RegexError(f"unsupported schema: {json.dumps(schema)[:120]}")


def json_value_regex(max_depth: int = 4) -> str:
    """Generic JSON value, bracket nesting bounded at `max_depth` (a
    regular approximation of the context-free JSON grammar)."""
    scalar = f"({_STRING}|{_NUMBER}|{_BOOLEAN}|{_NULL})"
    value = scalar
    for _ in range(max_depth):
        arr = rf"\[({value}(,{_WS}{value})*)?\]"
        obj = (rf"\{{({_STRING}:{_WS}{value}"
               rf"(,{_WS}{_STRING}:{_WS}{value})*)?\}}")
        value = f"({scalar}|{arr}|{obj})"
    return value


def json_object_regex(max_depth: int = 4) -> str:
    """response_format json_object: the top level must be an object."""
    value = json_value_regex(max_depth - 1)
    return (rf"\{{({_STRING}:{_WS}{value}"
            rf"(,{_WS}{_STRING}:{_WS}{value})*)?\}}")


def tool_call_regex(format_name: str, tools: list,
                    specific: Optional[str] = None) -> str:
    """Output grammar for a FORCED tool call (OpenAI tool_choice
    'required' / named function): the call JSON is constrained to a
    declared function name + its parameter schema, wrapped in the
    model's tool-parser format so the parser extracts it losslessly.
    """
    fmt = (format_name or "").lower()
    args_key = "parameters" if fmt == "llama3_json" else "arguments"
    calls = []
    for tool in tools or []:
        fn = tool.get("function", tool) if isinstance(tool, dict) else {}
        name = fn.get("name")
        if not isinstance(name, str) or not name:
            continue
        if specific is not None and name != specific:
            continue
        params = fn.get("parameters")
        args_re = schema_to_regex(params) if params else \
            json_object_regex()
        calls.append(
            rf'\{{"name":{_WS}"{_re_escape(name)}",{_WS}'
            rf'"{args_key}":{_WS}{args_re}\}}')
    if not calls:
        raise RegexError(
            f"tool_choice names no declared function "
            f"({specific!r} not in tools)" if specific is not None
            else "tool_choice 'required' needs non-empty tools")
    call = "(" + "|".join(calls) + ")"
    if fmt in ("hermes", "qwen"):
        return rf"<tool_call>\n?{call}\n?</tool_call>"
    if fmt == "llama3_json":
        return call  # the whole message IS the call object
    if fmt == "mistral":
        return rf"\[TOOL_CALLS\] ?\[{call}\]"
    raise RegexError(
        f"tool_choice forcing is not supported for tool parser "
        f"{format_name!r} (hermes/qwen, llama3_json, mistral)")


# ---------------------------------------------------------------------------
# the logits processor

class GuidedProcessor:
    """BaseLogitsProcessor enforcing a DFA constraint. Masks the next-
    token logits to transitions that keep the DFA alive; EOS rows stay
    legal only at accepting states. On a dead state (shouldn't happen
    under its own masking) it forces EOS rather than emit garbage."""

    def __init__(self, guide: TokenGuide) -> None:
        self.guide = guide
        self.state = 0
        self._consumed = 0

    def __call__(self, input_ids: Sequence[int],
                 logits: np.ndarray) -> None:
        for tok in list(input_ids)[self._consumed:]:
            if self.state >= 0:
                self.state = self.guide.advance(self.state, int(tok))
            self._consumed += 1
        eos = [e for e in self.guide.eos_ids if e < logits.shape[-1]]
        if self.state < 0:
            logits[:] = -np.inf
            for e in eos:
                logits[e] = 0.0
            return
        mask = self.guide.allowed(self.state)[:logits.shape[-1]]
        keep = np.zeros(logits.shape[-1], bool)
        keep[:mask.shape[0]] = mask
        if self.guide.eos_allowed(self.state):
            for e in eos:
                keep[e] = True
        if not keep.any():
            for e in eos:
                keep[e] = True
        logits[~keep] = -np.inf


_GUIDE_CACHE: dict = {}


def make_guided_processor(tokenizer=None, *, regex: Optional[str] = None,
                          choice: Optional[list] = None,
                          json_schema: Optional[dict] = None,
                          json_object: bool = False,
                          tool_call: Optional[dict] = None,
                          ) -> GuidedProcessor:
    """Factory registered as the 'guided' logits processor. Exactly one
    of regex / choice / json_schema / json_object / tool_call selects
    the grammar. Compiled TokenGuides are cached per (tokenizer,
    pattern) — schema compilation and vocab mask precomputation amortize
    across requests.
    """
    given = [regex is not None, choice is not None,
             json_schema is not None, bool(json_object),
             tool_call is not None]
    if sum(given) != 1:
        raise ValueError(
            "guided decoding needs exactly one of regex / choice / "
            "json_schema / json_object / tool_call")
    if tokenizer is None:
        raise ValueError("guided decoding needs the worker tokenizer")
    if regex is not None:
        pattern = regex
    elif choice is not None:
        if not choice or not all(isinstance(c, str) for c in choice):
            raise ValueError("choice must be a non-empty string list")
        pattern = "(" + "|".join(_re_escape(c) for c in choice) + ")"
    elif json_schema is not None:
        pattern = schema_to_regex(json_schema)
    elif tool_call is not None:
        pattern = tool_call_regex(tool_call.get("format", ""),
                                  tool_call.get("tools") or [],
                                  tool_call.get("name"))
    else:
        pattern = json_object_regex()
    key = (id(tokenizer), pattern)
    entry = _GUIDE_CACHE.get(key)
    if entry is None:
        dfa = compile_regex(pattern)
        guide = TokenGuide(dfa, token_bytes_for(tokenizer),
                           getattr(tokenizer, "eos_token_ids", []))
        if len(_GUIDE_CACHE) > 64:
            _GUIDE_CACHE.clear()
        # hold the tokenizer so its id cannot be recycled underneath
        # the cache key while this entry lives
        _GUIDE_CACHE[key] = (tokenizer, guide)
    else:
        guide = entry[1]
    return GuidedProcessor(guide)
