"""Global router: hierarchical routing across pool namespaces.

The reference's `dynamo.global_router` (ref: components/src/dynamo/
global_router/{handler,pool_selection}.py, README.md:9-17) sits above
multiple Dynamo deployments ("pools" — each its own namespace with a
frontend-less worker fleet), picks a pool per request, and registers
ITSELF as both a Chat/Completions and a Prefill model so ordinary
frontends discover and route to it like any worker.

Here: one ModelWatcher per pool namespace maintains a live pipeline to
that pool's workers (KV events and load metrics flow per-pool exactly as a
frontend's would); pool selection picks by aggregate load or round-robin;
the chosen pool's engine streams back through our own `generate` endpoint
published in the global namespace.
"""

from __future__ import annotations

import asyncio
import itertools
from typing import AsyncIterator, Optional

from ..kv_router import KvRouterConfig
from ..llm.manager import ModelManager, ModelWatcher
from ..llm.model_card import CHAT, COMPLETIONS, PREFILL, ModelDeploymentCard, publish_card
from ..llm.protocols import EngineOutput, PreprocessedRequest
from ..runtime import DistributedRuntime, new_instance_id
from ..runtime.admission import AdmissionRefused
from ..runtime.logging import get_logger
from ..runtime.push_router import NoInstancesAvailable

log = get_logger("global_router")

POLICIES = ("least_loaded", "round_robin")


class Pool:
    """One downstream deployment: a namespace watched for model cards."""

    def __init__(self, namespace: str, manager: ModelManager,
                 watcher: ModelWatcher) -> None:
        self.namespace = namespace
        self.manager = manager
        self.watcher = watcher

    def entry(self, model: str):
        entry, lora = self.manager.resolve(model)
        return entry

    def load(self, model: str) -> Optional[float]:
        """Mean published KV usage across the pool's live instances for
        `model`; None when the pool doesn't serve it (or nothing has
        published yet — treated as idle by the selector)."""
        entry = self.entry(model)
        if entry is None or not entry.instances:
            return None
        usages = [entry.worker_usage[i] for i in entry.instances
                  if i in entry.worker_usage]
        if not usages:
            return 0.0
        return sum(usages) / len(usages)


class GlobalRouter:
    def __init__(
        self,
        runtime: DistributedRuntime,
        pool_namespaces: list[str],
        served_model: str,
        global_namespace: str = "global",
        policy: str = "least_loaded",
        router_mode: str = "kv",
        kv_config: Optional[KvRouterConfig] = None,
        federation=None,
    ) -> None:
        assert policy in POLICIES, f"policy must be one of {POLICIES}"
        self.runtime = runtime
        self.served_model = served_model
        self.policy = policy
        # Optional federation.FederationRouter: when set, pool selection
        # is residency-first (cells are pool namespaces) and a refused
        # decision sheds with Retry-After instead of piling onto a
        # saturated fleet. None = the pre-federation policies.
        self.federation = federation
        self.instance_id = new_instance_id()
        self.pools: list[Pool] = []
        for ns in pool_namespaces:
            manager = ModelManager()
            watcher = ModelWatcher(runtime, manager, router_mode=router_mode,
                                   kv_config=kv_config,
                                   namespace_filter=ns)
            self.pools.append(Pool(ns, manager, watcher))
        self._rr = itertools.count()
        # Register as BOTH chat/completions and prefill (ref README: the
        # global router appears as a Prefill and a Chat model).
        self.card = ModelDeploymentCard(
            name=served_model,
            model_types=[CHAT, COMPLETIONS, PREFILL],
            namespace=global_namespace,
            component="global_router",
            endpoint="generate",
        )
        self._served = None

    # -- pool selection (ref: pool_selection.py) ---------------------------

    def select_pool(self, model: str,
                    session_id: Optional[str] = None) -> Optional[Pool]:
        serving = [p for p in self.pools if p.entry(model) is not None]
        if not serving:
            return None
        if self.federation is not None:
            pool = self._select_federated(serving, session_id)
            if pool is not None:
                return pool
            # The federation's pick doesn't serve this model (mixed
            # fleets): fall through to the plain policies.
        if self.policy == "round_robin" or len(serving) == 1:
            return serving[next(self._rr) % len(serving)]
        # least_loaded: idle pools (no published metrics yet) sort first.
        return min(serving, key=lambda p: p.load(model) or 0.0)

    def _select_federated(self, serving: list[Pool],
                          session_id: Optional[str]) -> Optional[Pool]:
        """Residency-first selection: the federation router picks a
        cell, cells ARE pool namespaces. Raises AdmissionRefused when
        every cell is past the spill threshold (the frontend already
        maps that to 503 + Retry-After)."""
        decision = self.federation.route(session_id)
        if decision.outcome == "refused":
            raise self.federation.refusal(decision)
        for pool in serving:
            if pool.namespace == decision.cell:
                return pool
        return None

    # -- serving ------------------------------------------------------------

    async def generate(self, body: dict, ctx=None) -> AsyncIterator[dict]:
        request = PreprocessedRequest.from_wire(body)
        model = request.model or self.served_model
        try:
            pool = self.select_pool(model, session_id=request.session_id)
        except AdmissionRefused as refused:
            # Saturated federation: honest shed, never a silent queue.
            yield EngineOutput(
                finish_reason="error",
                error=(f"{refused} (retry after "
                       f"{refused.retry_after_s:.0f}s)"),
            ).to_wire()
            return
        if pool is None:
            yield EngineOutput(
                finish_reason="error",
                error=f"no pool serves model {model!r}",
            ).to_wire()
            return
        entry = pool.entry(model)
        try:
            async for output in entry.engine.generate(request):
                yield output.to_wire()
        except NoInstancesAvailable:
            yield EngineOutput(
                finish_reason="error",
                error=f"pool {pool.namespace} has no live instances",
            ).to_wire()

    async def start(self) -> None:
        for pool in self.pools:
            await pool.watcher.start()
        endpoint = (
            self.runtime.namespace(self.card.namespace)
            .component(self.card.component)
            .endpoint(self.card.endpoint)
        )
        self._served = await endpoint.serve_endpoint(
            self.generate, instance_id=self.instance_id)
        await publish_card(self.runtime, self.card, self.instance_id)
        log.info("global router serving %s over pools %s (policy=%s)",
                 self.served_model,
                 [p.namespace for p in self.pools], self.policy)

    async def close(self) -> None:
        if self._served is not None:
            await self._served.shutdown()
        for pool in self.pools:
            await pool.watcher.close()


async def main(argv: Optional[list[str]] = None) -> None:
    import argparse

    from ..runtime import RuntimeConfig
    from ..runtime.signals import wait_for_shutdown_signal

    parser = argparse.ArgumentParser("dynamo_tpu.global_router")
    parser.add_argument("--pool", action="append", required=True,
                        dest="pools", metavar="NAMESPACE",
                        help="pool namespace to route over (repeatable)")
    parser.add_argument("--model", required=True,
                        help="model name this router serves")
    parser.add_argument("--namespace", default="global")
    parser.add_argument("--policy", default="least_loaded", choices=POLICIES)
    parser.add_argument("--router-mode", default="kv",
                        choices=["round_robin", "random", "p2c", "kv"],
                        help="intra-pool routing mode")
    args = parser.parse_args(argv)
    runtime = await DistributedRuntime(RuntimeConfig.from_env()).start()
    router = GlobalRouter(
        runtime, args.pools, args.model,
        global_namespace=args.namespace, policy=args.policy,
        router_mode=args.router_mode,
    )
    await router.start()
    try:
        await wait_for_shutdown_signal()
    finally:
        await router.close()
        await runtime.shutdown()
