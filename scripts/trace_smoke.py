#!/usr/bin/env python
"""Trace smoke: mocker loadgen pass against an OTLP collector stub.

CI entrypoint (the `trace-smoke` job): bring up a mocker worker and the
OpenAI frontend on in-process planes, point DYNT_OTLP_ENDPOINT at a
local collector stub, run a short burst of chat requests with
DYNT_SLOW_TRACE_MS enabled, then assert that

  * the collector received a nonzero number of spans, including the
    frontend -> router -> (mocker) chain sharing one trace per request,
  * the frontend's /debug/requests flight recorder is populated with
    completed timelines (flagged slow by the forced threshold),

and write both the exported trace JSON and the recorder snapshot as CI
artifacts. Exits nonzero on any violated invariant.

Usage: python scripts/trace_smoke.py [--requests N] [--out DIR]
"""

from __future__ import annotations

import argparse
import asyncio
import http.server
import json
import os
import pathlib
import sys
import threading
import uuid

# Runnable as `python scripts/trace_smoke.py` from the repo root.
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

REQUEST_TIMEOUT = 60.0


def start_collector():
    class Collector(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length))
            self.server.captured.append((self.path, payload))
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *args):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Collector)
    srv.captured = []
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def spans_of(srv):
    spans = []
    for _path, payload in srv.captured:
        for rs in payload.get("resourceSpans", []):
            for ss in rs.get("scopeSpans", []):
                spans.extend(ss.get("spans", []))
    return spans


async def run_pass(n_requests: int):
    import aiohttp

    from dynamo_tpu.frontend import Frontend
    from dynamo_tpu.mocker import MockerConfig, MockerWorker
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    cfg = RuntimeConfig.from_env()
    cfg.discovery_backend = "mem"
    cfg.discovery_path = uuid.uuid4().hex
    cfg.request_plane = "mem"
    cfg.event_plane = "mem"
    cfg.system_enabled = False

    rt = await DistributedRuntime(cfg).start()
    worker = MockerWorker(
        rt, model_name="mock-model",
        config=MockerConfig(speedup_ratio=500.0, num_blocks=256),
        load_publish_interval=0.2)
    await worker.start()
    frontend = Frontend(rt, host="127.0.0.1", port=0,
                        router_mode="round_robin")
    await frontend.start()
    for _ in range(100):
        if frontend.manager.get("mock-model") is not None:
            break
        await asyncio.sleep(0.05)
    else:
        raise RuntimeError("mocker never registered with the frontend")

    base = f"http://127.0.0.1:{frontend.port}"

    async def one_request(session, i):
        payload = {
            "model": "mock-model",
            "messages": [{"role": "user",
                          "content": f"trace smoke request {i}"}],
            "max_tokens": 8,
        }
        async with session.post(f"{base}/v1/chat/completions",
                                json=payload) as resp:
            body = await resp.json()
            assert resp.status == 200, body
            return body

    async with aiohttp.ClientSession() as session:
        await asyncio.gather(*[one_request(session, i)
                               for i in range(n_requests)])
        async with session.get(f"{base}/debug/requests") as resp:
            snapshot = await resp.json()

    from dynamo_tpu.runtime.otel import get_tracer

    await asyncio.to_thread(get_tracer().flush)
    await frontend.close()
    await worker.close()
    await rt.shutdown()
    return snapshot


def main() -> int:
    parser = argparse.ArgumentParser("trace_smoke")
    parser.add_argument("--requests", type=int, default=8)
    parser.add_argument("--out", default=".",
                        help="artifact directory (trace-smoke-spans.json "
                             "+ trace-smoke-recorder.json)")
    args = parser.parse_args()

    srv, endpoint = start_collector()
    # Must be set before the first get_tracer()/get_recorder() call.
    os.environ["DYNT_OTLP_ENDPOINT"] = endpoint
    os.environ.setdefault("DYNT_SLOW_TRACE_MS", "1")
    os.environ.setdefault("DYNT_DEBUG_ENDPOINTS", "1")

    snapshot = asyncio.run(
        asyncio.wait_for(run_pass(args.requests), REQUEST_TIMEOUT))
    spans = spans_of(srv)
    srv.shutdown()

    out = pathlib.Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    (out / "trace-smoke-spans.json").write_text(
        json.dumps(spans, indent=2))
    (out / "trace-smoke-recorder.json").write_text(
        json.dumps(snapshot, indent=2))

    failures = []
    if not spans:
        failures.append("no spans reached the collector stub")
    names = {s["name"] for s in spans}
    for required in ("http.chat", "router.dispatch"):
        if required not in names:
            failures.append(f"span {required!r} missing (got {sorted(names)})")
    http_spans = [s for s in spans if s["name"] == "http.chat"]
    traces = {s["traceId"] for s in http_spans}
    if len(traces) != args.requests:
        failures.append(f"expected {args.requests} traces, "
                        f"saw {len(traces)}")
    # every dispatch parents under an http span of the same trace
    by_id = {s["spanId"]: s for s in spans}
    for s in spans:
        if s["name"] == "router.dispatch":
            parent = by_id.get(s.get("parentSpanId", ""))
            if parent is None or parent["traceId"] != s["traceId"]:
                failures.append("router.dispatch span with broken parentage")
                break
    completed = snapshot.get("completed", [])
    if len(completed) < args.requests:
        failures.append(f"/debug/requests has {len(completed)} completed "
                        f"timelines, expected >= {args.requests}")
    if not any(t.get("slow") for t in completed):
        failures.append("DYNT_SLOW_TRACE_MS=1 set but no timeline "
                        "flagged slow")
    if not all({"received", "first_token", "finished"}
               <= set(t.get("phases", {})) for t in completed):
        failures.append("completed timelines missing phase timestamps")

    print(f"trace-smoke: {len(spans)} spans, {len(traces)} traces, "
          f"{len(completed)} recorded timelines")
    for failure in failures:
        print(f"FAIL: {failure}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
