"""Chaos-two-tenant CI driver: an interactive tenant at a fixed
below-knee rate and a batch tenant ramping ~2x past the capacity knee,
A/B'd against the identical traffic untagged (pure FCFS), through the
full in-process QoS plane — priority classes on the wire, weighted
fair-share quotas, class-strict queues, and preempt-to-park scheduling
(docs/multi-tenancy.md).

Headless, CPU-only, chip-free. Writes the JSON report the
chaos-two-tenant job uploads as an artifact and exits nonzero when any
scenario assertion fails — the CI gate on the QoS contract:

    python scripts/chaos_tenants.py --out chaos-two-tenant
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser("chaos_tenants")
    parser.add_argument("--out", default="chaos-two-tenant",
                        help="report output directory")
    parser.add_argument("--quick", action="store_true",
                        help="shorter ramp (local smoke)")
    args = parser.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("DYNT_LOG_LEVEL", "WARNING")

    from dynamo_tpu.mocker.overload import (
        TwoTenantParams,
        run_two_tenant_scenario,
    )

    params = TwoTenantParams()
    if args.quick:
        params = TwoTenantParams(ramp_secs=16.0, batch_end_rps=20.0)
    report = asyncio.run(run_two_tenant_scenario(params))

    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "chaos_two_tenant_report.json")
    with open(path, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
    print(f"report: {path}")
    for check in report["assertions"]:
        status = "PASS" if check["ok"] else "FAIL"
        print(f"  [{status}] {check['name']}")
    if not report["passed"]:
        print("two-tenant QoS assertions FAILED", file=sys.stderr)
        return 1
    print("two-tenant QoS assertions passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
