"""Batch-scaling ablation: WHICH part of the ctx~0 decode floor grows
with batch size?

The r3 probe (bench_probe.py) showed the weights-only floor rising
2,580 -> 3,298 -> 5,241 us/step from bs 8 -> 16 -> 32 while the streamed
bytes stay constant — so something batch-linear eats the headroom. This
probe times jitted scan-blocks of ablated programs on the real chip:

  matmuls   just the per-layer matmul chain (weight streaming + MXU)
  +vpu      plus norms/rope/activation (batch-linear VPU work)
  +head     plus the LM head matmul + logits materialization
  +sample   plus the sampler (full forward_decode equivalent)

One JSON line per (config, bs). Scan-block timing per the tunnel rule:
only deferred, scanned programs give valid numbers here.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BLOCK = 64
N_BLOCKS = 4


def build(config, params, bs, what):
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.sampler import sample
    from dynamo_tpu.models.transformer import rms_norm, rope

    h_dim = config.hidden

    def layer_matmuls(x, lp):
        q = jnp.einsum("bh,hqd->bqd", x, lp["wq"])
        k = jnp.einsum("bh,hkd->bkd", x, lp["wk"])
        v = jnp.einsum("bh,hkd->bkd", x, lp["wv"])
        attn = q[:, :, :] * 1.0  # stand-in for attention output
        o = jnp.einsum("bqd,qdh->bh", attn, lp["wo"].reshape(
            config.n_q_heads, config.head_dim, h_dim))
        g = jnp.einsum("bh,hm->bm", o, lp["w_gate"])
        u = jnp.einsum("bh,hm->bm", o, lp["w_up"])
        d = jnp.einsum("bm,mh->bh", g * u, lp["w_down"])
        return x + d * 1e-6, k, v

    def layer_full(x, lp, positions):
        hn = rms_norm(x[:, None, :], lp["attn_norm"], config.rms_eps)[:, 0]
        q = jnp.einsum("bh,hqd->bqd", hn, lp["wq"])
        k = jnp.einsum("bh,hkd->bkd", hn, lp["wk"])
        v = jnp.einsum("bh,hkd->bkd", hn, lp["wv"])
        if config.qk_norm:
            q = rms_norm(q, lp["q_norm"], config.rms_eps)
            k = rms_norm(k, lp["k_norm"], config.rms_eps)
        q = rope(q[:, None], positions[:, None], config.rope_theta)[:, 0]
        k = rope(k[:, None], positions[:, None], config.rope_theta)[:, 0]
        attn = q * 1.0
        o = jnp.einsum("bqd,qdh->bh", attn, lp["wo"].reshape(
            config.n_q_heads, config.head_dim, h_dim))
        x = x + o
        hn = rms_norm(x[:, None, :], lp["mlp_norm"], config.rms_eps)[:, 0]
        g = jnp.einsum("bh,hm->bm", hn, lp["w_gate"])
        u = jnp.einsum("bh,hm->bm", hn, lp["w_up"])
        d = jnp.einsum("bm,mh->bh", jax.nn.silu(g) * u, lp["w_down"])
        return x + d, k, v

    def body(carry, _):
        tokens, positions = carry
        x = params["embed"][tokens]
        for lp in params["layers"]:
            if what == "matmuls":
                x, _k, _v = layer_matmuls(x, lp)
            else:
                x, _k, _v = layer_full(x, lp, positions)
        if what in ("matmuls", "+vpu"):
            nxt = jnp.argmax(x, axis=-1).astype(jnp.int32) % 1000
            return (nxt, positions + 1), nxt
        x = rms_norm(x[:, None, :], params["final_norm"],
                     config.rms_eps)[:, 0]
        head = (params["embed"].T if config.tie_embeddings
                else params["lm_head"])
        logits = (x @ head).astype(jnp.float32)
        if what == "+head":
            nxt = jnp.max(logits, axis=-1).astype(jnp.int32) % 1000
            return (nxt, positions + 1), nxt
        nxt = sample(logits, jnp.zeros(bs), jnp.ones(bs),
                     jnp.zeros(bs, jnp.int32), jnp.zeros(bs, jnp.uint32),
                     positions)
        return (nxt, positions + 1), nxt

    def block_fn(tokens, positions):
        (t, p), toks = jax.lax.scan(body, (tokens, positions), None,
                                    length=BLOCK)
        return toks

    return jax.jit(block_fn)


def run(bs, what):
    import jax

    from dynamo_tpu.engine.model_runner import ModelRunner, RunnerConfig
    from dynamo_tpu.models import get_config
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    config = get_config("qwen3-0.6b")
    runner = ModelRunner(
        config,
        RunnerConfig(page_size=16, num_pages=64, max_batch=bs,
                     max_pages_per_seq=4, prefill_buckets=(32,)),
        make_mesh(MeshConfig()), seed=0)
    fn = build(config, runner.params, bs, what)
    tokens = np.zeros(bs, np.int32)
    positions = np.zeros(bs, np.int32)
    out = fn(tokens, positions)
    np.asarray(out)  # compile + settle
    trials = []
    for _ in range(3):
        t0 = time.perf_counter()
        pending = []
        for _ in range(N_BLOCKS):
            pending.append(fn(tokens, positions))
        for p in pending:
            np.asarray(p)
        trials.append(time.perf_counter() - t0)
    best = sorted(trials)[1]
    us = 1e6 * best / (N_BLOCKS * BLOCK)
    print(json.dumps({"what": what, "bs": bs,
                      "us_per_step": round(us, 1)}), flush=True)


def main():
    import gc

    whats = (sys.argv[1].split(",") if len(sys.argv) > 1
             else ["matmuls", "+vpu", "+head", "+sample"])
    sizes = ([int(b) for b in sys.argv[2].split(",")]
             if len(sys.argv) > 2 else [8, 32])
    for what in whats:
        for bs in sizes:
            run(bs, what)
            gc.collect()


if __name__ == "__main__":
    main()
