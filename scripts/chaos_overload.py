"""Chaos-overload CI driver: ramp an open-loop mocker load past the
capacity knee with the admission loop off/on, sweep P/D splits, assert
graceful degradation, and write the goodput-vs-load JSON report the CI
job uploads as an artifact (docs/fault-tolerance.md chaos how-to).

Headless, CPU-only, chip-free: everything runs in-process through
dynamo_tpu.mocker.overload. Exits nonzero when any scenario assertion
fails, so the chaos-overload job gates on the degradation contract.

    python scripts/chaos_overload.py --out chaos-overload
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser("chaos_overload")
    parser.add_argument("--out", default="chaos-overload",
                        help="report output directory")
    parser.add_argument("--quick", action="store_true",
                        help="shorter ramp/sweep (local smoke)")
    parser.add_argument("--no-pd-sweep", action="store_true",
                        help="skip the P/D split sweep phase")
    args = parser.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("DYNT_LOG_LEVEL", "WARNING")

    from dynamo_tpu.mocker.overload import OverloadParams, run_scenario

    params = OverloadParams()
    if args.quick:
        params = OverloadParams(ramp_secs=16.0, ramp_end_rps=28.0,
                                bucket_secs=4.0, sweep_secs=6.0)
    report = asyncio.run(run_scenario(params,
                                      pd_sweep=not args.no_pd_sweep))

    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "chaos_overload_report.json")
    with open(path, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)

    print(f"report: {path}")
    for chk in report["assertions"]:
        mark = "PASS" if chk["ok"] else "FAIL"
        print(f"  [{mark}] {chk['name']}")
        if not chk["ok"]:
            print(f"         {json.dumps(chk['detail'])[:400]}")
    curve = [(b["offered_rps"], b["goodput_rps"], b["shed_frac"])
             for b in report["ramp_on"]["buckets"]]
    print("goodput-vs-load (loop on): "
          + " ".join(f"{o:.1f}->{g:.1f}({s:.0%})" for o, g, s in curve))
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
