#!/usr/bin/env bash
# Vendor a pinned real etcd into build/etcd/ so the real-backend class in
# tests/test_etcd_discovery.py runs (VERDICT r2 weak #5: the etcd client
# had only ever been exercised against the in-process stub). Run on any
# box with network; zero-egress dev sandboxes rely on CI for this tier.
set -euo pipefail

ETCD_VERSION="${ETCD_VERSION:-v3.5.16}"
ARCH="$(uname -m)"
case "$ARCH" in
  x86_64) GOARCH=amd64 ;;
  aarch64|arm64) GOARCH=arm64 ;;
  *) echo "unsupported arch: $ARCH" >&2; exit 1 ;;
esac

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
DEST="$ROOT/build/etcd"
mkdir -p "$DEST"
TARBALL="etcd-${ETCD_VERSION}-linux-${GOARCH}.tar.gz"
URL="https://github.com/etcd-io/etcd/releases/download/${ETCD_VERSION}/${TARBALL}"

echo "fetching $URL"
curl -fsSL -o "$DEST/$TARBALL" "$URL"
tar -xzf "$DEST/$TARBALL" -C "$DEST" --strip-components=1 \
    "etcd-${ETCD_VERSION}-linux-${GOARCH}/etcd"
rm "$DEST/$TARBALL"
"$DEST/etcd" --version
echo "etcd vendored at $DEST/etcd (DYNT_ETCD_BIN=$DEST/etcd)"
