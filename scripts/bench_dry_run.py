"""Bench dry run: every wired bench.py block at toy sizes, on CPU.

`python bench.py` on silicon is a once-per-round capture; nothing in
CI exercised its block wiring between rounds, so a refactor could rot
a block (an import, a knob rename, a summary-key drift) and the
breakage would surface mid-capture on the chip. This smoke drives the
SAME functions bench.py's main() dispatches to — the model bench with
its spec and kvbm_offload blocks, plus every mocker-backed point —
with sizes shrunk to seconds-scale, and fails if any required block is
missing or errored.

Run: python scripts/bench_dry_run.py          (CI: bench-dry-run job)
Prints one JSON line mirroring bench.py's report shape; `--json PATH`
also writes it to a file — the input tools/dynawatch gates against its
blessed baselines (CI: obs-watch job).
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("DYNT_LOG_LEVEL", "WARNING")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REQUIRED_BLOCKS = ("spec", "kvbm_offload", "disagg", "q4_ablation",
                   "session_cache", "two_class_goodput", "drain",
                   "cold_start")


def main() -> int:
    parser = argparse.ArgumentParser("bench_dry_run")
    parser.add_argument("--json", default="",
                        help="also write the report to this path")
    args = parser.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import bench
    from dynamo_tpu.perf.q4_ablation import run_ablation

    # The model bench at toy sizes: one decode block, spec + kvbm
    # blocks on, prefill/ttft off (not capture blocks — pure runtime).
    result = bench.bench_one(
        "qwen3-0.6b", batch=2, prompt_len=64, decode_steps=64,
        num_pages=128, prefill_chunk=256, do_prefill=False,
        do_ttft=False, device_kind="cpu")

    # Kernel parity sweep in interpret mode, one tiny point per layout.
    result["q4_ablation"] = run_ablation(
        mode="interpret", m=2, bns=(512,), gks=(0,),
        geoms=(("k512", 512, 512),), trials=1, steps=2)

    # The mocker-backed points, exactly as bench.py main() wires them,
    # with every exposed size knob shrunk.
    result["disagg"] = bench.bench_disagg_point(requests=4)
    result["session_cache"] = bench.bench_session_point()
    result["two_class_goodput"] = bench.bench_two_class_point()
    result["drain"] = bench.bench_drain_point()
    result["cold_start"] = bench.bench_cold_start_point()

    print(json.dumps(result))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(result, fh)

    failures = []
    for key in REQUIRED_BLOCKS:
        block = result.get(key)
        if not isinstance(block, dict):
            failures.append(f"{key}: missing")
        elif "error" in block:
            failures.append(f"{key}: {block['error']}")
    # The chaos-backed points carry their own pass verdicts.
    if result["drain"].get("passed") is not True:
        failures.append("drain: scenario assertions failed")
    if result["cold_start"]["measured_spot"].get("passed") is not True:
        failures.append("cold_start: spot scenario assertions failed")
    if result["q4_ablation"].get("parity_failures"):
        failures.append("q4_ablation: parity failed")
    if failures:
        print("bench dry run FAILED:\n  " + "\n  ".join(failures),
              file=sys.stderr)
        return 1
    print(f"bench dry run ok: {len(REQUIRED_BLOCKS)} blocks",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
