"""Floor ablation v2: reuse the PROVEN decode_multi timing path
(bench_probe.run_config) with surgical monkeypatches, instead of a
bespoke program the remote compiler chokes on.

  full        unmodified decode (bench_probe baseline)
  noscatter   write_kv_stack -> identity (no paged-pool writeback)
  nosample    sampler.sample -> zeros (no argmax/logits consumer)
  nohead      lm head matmul + logits replaced by a [B,1] dummy read

Usage: python -u scripts/bench_ablate2.py <what> <bs>
(one config per process: monkeypatches must precede jit builds)."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def apply_patch(what: str) -> None:
    import jax.numpy as jnp

    from dynamo_tpu.models import transformer

    if what == "noscatter":
        transformer.write_kv_stack = (
            lambda kv_cache, *a, **k: kv_cache)
    elif what == "nosample":
        from dynamo_tpu.engine import sampler

        sampler.sample = (
            lambda logits, temperature, *a, **k:
            jnp.zeros(logits.shape[0], jnp.int32))
        # model_runner imported sample by name
        from dynamo_tpu.engine import model_runner

        model_runner.sample = sampler.sample
    elif what == "nohead":
        orig = transformer.forward_decode

        def patched(params, config, tokens, *a, **k):
            kv, logits = orig(params, config, tokens, *a, **k)
            # keep the output contract but drop the real logits so XLA
            # dead-code-eliminates the head matmul + [B, V] materialize
            fake = jnp.zeros((logits.shape[0], logits.shape[1], 1024),
                             jnp.float32) + tokens[:, None, None]
            return kv, fake
        transformer.forward_decode = patched
        from dynamo_tpu.engine import model_runner

        model_runner.forward_decode = patched
    elif what == "norope":
        transformer.rope = lambda x, positions, theta=10000.0: x
    elif what == "noqknorm":
        # skip q/k per-head norms only (qwen3 qk_norm): patch the config
        # factory (bench_probe late-imports get_config from the package)
        import dataclasses as dc

        import dynamo_tpu.models as m
        from dynamo_tpu.models import config as mcfg

        orig_get = mcfg.get_config

        def patched_cfg(name):
            return dc.replace(orig_get(name), qk_norm=False)
        mcfg.get_config = patched_cfg
        m.get_config = patched_cfg
    elif what == "nonorm":
        transformer.rms_norm = lambda x, w, eps=1e-6: x
    elif what != "full":
        raise SystemExit(f"unknown ablation {what}")


def main() -> None:
    what = sys.argv[1]
    bs = int(sys.argv[2])
    apply_patch(what)
    from scripts.bench_probe import run_config

    run_config(f"{what}-bs{bs}", bs, 0, "pallas")


if __name__ == "__main__":
    main()
