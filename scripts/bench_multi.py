"""Multi-configuration benchmarks -> BENCH_MULTI.json (+ markdown table).

Covers the BASELINE.json configs beyond the single-chip decode bench
(which bench.py owns), in the same tiers the reference uses for its router
and disagg numbers (mocker-backed A/B at controlled prefix ratios — ref:
benchmarks/router/prefix_ratio_benchmark.py — and offline agg/disagg
replay), plus a real-engine KVBM onboard TTFT curve:

  router_ab   8 mocker workers, kv-aware vs round-robin routing at prefix
              ratios {0.1, 0.5, 0.9}  (BASELINE config 2 analog)
  disagg      aggregated vs disaggregated prefill/decode offline replay
              (BASELINE config 3 analog)
  kvbm_ttft   real JAX engine, TTFT of a long-prefix re-sent prompt: cold
              vs G1 prefix-cache hit vs G2 host-tier onboard after the G1
              pages were evicted  (BASELINE config 4 analog)

Everything runs on CPU (mocker simulation + tiny real engine): the numbers
are A/B RELATIVE — exactly how the reference publishes its router (3x
TTFT) and disagg wins — not absolute chip throughput (bench.py measures
that on the real chip).

Run:  python scripts/bench_multi.py [--quick] [--out BENCH_MULTI.json]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time

sys.path.insert(0, ".")


def bench_router_ab(quick: bool) -> dict:
    from dynamo_tpu.mocker.engine import MockerConfig
    from dynamo_tpu.mocker.loadgen import OfflineReplay, synthesize_trace

    # Regime note (measured, r3): KV-aware routing's TTFT win appears when
    # per-worker KV capacity cannot hold every hot prefix — here 8 prefix
    # groups of ~115 blocks vs 600 blocks/worker, so round-robin thrashes
    # every cache while KV routing pins groups to workers (the reference's
    # 3x claim is the same capacity-constrained shape: 70B on 2 nodes, 4K
    # ISL — architecture.md:159). With oversized caches or near-free
    # simulated compute, RR converges to the same hit rate and the A/B
    # measures only queue noise.
    # n pinned to the thrash window: much longer runs let round-robin's
    # LRUs stabilize on a recent-groups working set and the A/B converges.
    n = 80 if quick else 120
    out = {}
    for prefix_ratio in (0.1, 0.5, 0.9):
        row = {}
        trace = synthesize_trace(
            n, rate_rps=3.0, isl_mean=2048, osl_mean=32,
            prefix_ratio=prefix_ratio, num_prefix_groups=8, seed=7)
        for policy in ("round_robin", "kv"):
            replay = OfflineReplay(
                mode="agg", num_workers=4, router_policy=policy,
                config=MockerConfig(speedup_ratio=5.0, num_blocks=600))
            report = asyncio.run(replay.run(trace))
            assert report.errors == 0, report.summary()
            row[policy] = report.summary()
        kv50 = row["kv"]["ttft_ms"]["p50"] or 1e-9
        row["kv_ttft_speedup_p50"] = round(
            row["round_robin"]["ttft_ms"]["p50"] / kv50, 2)
        out[f"prefix_{prefix_ratio}"] = row
    return out


def bench_disagg(quick: bool) -> dict:
    from dynamo_tpu.mocker.engine import MockerConfig
    from dynamo_tpu.mocker.loadgen import OfflineReplay, synthesize_trace

    n = 100 if quick else 400
    trace = synthesize_trace(
        n, rate_rps=30.0, isl_mean=3072, osl_mean=128,
        prefix_ratio=0.3, seed=11)
    out = {}
    for mode, kwargs in (
        ("agg", dict(mode="agg", num_workers=4)),
        ("disagg", dict(mode="disagg", num_workers=3,
                        num_prefill_workers=1)),
    ):
        replay = OfflineReplay(
            router_policy="kv" if mode == "agg" else "round_robin",
            config=MockerConfig(speedup_ratio=100.0, num_blocks=4096),
            **kwargs)
        report = asyncio.run(replay.run(trace))
        assert report.errors == 0, report.summary()
        out[mode] = report.summary()
    # Disagg's headline: decode ITL stays flat because prefill bursts run
    # on the prefill pool (ref architecture.md disagg rationale).
    agg_itl = out["agg"]["itl_ms"]["p99"] or 1e-9
    out["disagg_itl_p99_improvement"] = round(
        agg_itl / (out["disagg"]["itl_ms"]["p99"] or 1e-9), 2)
    return out


def bench_kvbm_ttft(quick: bool) -> dict:
    """TTFT for a shared long prefix: cold prefill vs G1 prefix-cache hit
    vs G2 onboard (G1 pages evicted, host tier supplies the blocks)."""
    import numpy as np

    from dynamo_tpu.block_manager import (
        BlockLayoutSpec,
        KvBlockManager,
        KvbmConfig,
    )
    from dynamo_tpu.engine import InferenceScheduler, ModelRunner, RunnerConfig
    from dynamo_tpu.llm.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models import get_config

    from dynamo_tpu.parallel import MeshConfig, make_mesh

    runner = ModelRunner(
        get_config("tiny-test"),
        RunnerConfig(page_size=4, num_pages=96, max_batch=2,
                     max_pages_per_seq=48, prefill_buckets=(32, 64, 128)),
        make_mesh(MeshConfig()), seed=0)
    kvbm = KvBlockManager(
        KvbmConfig(host_blocks=256, offload_batch=4),
        BlockLayoutSpec.from_runner_layout(runner.kv_layout()))
    sched = InferenceScheduler(runner, kvbm=kvbm)
    sched.start()

    def one_request(tokens, tag):
        done = {}
        t0 = time.perf_counter()

        def emit(out):
            if "ttft" not in done and out.token_ids:
                done["ttft"] = (time.perf_counter() - t0) * 1e3
            if out.finish_reason is not None:
                done["fin"] = out.finish_reason

        sched.submit(PreprocessedRequest(
            request_id=tag, token_ids=list(tokens),
            sampling=SamplingOptions(max_tokens=4, temperature=0.0),
            stop=StopConditions(ignore_eos=True)), emit)
        deadline = time.time() + 120
        while "fin" not in done and time.time() < deadline:
            time.sleep(0.005)
        assert done.get("fin"), f"request {tag} never finished"
        return done["ttft"]

    try:
        prefix = list(np.arange(2, 122) % 500)  # 120 tokens, 30 blocks
        # Warm every prefill bucket + decode compile first: a G1 prefix
        # hit prefills only the short uncached SUFFIX, which uses a
        # different (smaller) bucket than the cold pass — on CPU that
        # bucket's first compile costs ~1s and would be billed to the
        # "hit" if not pre-compiled here.
        for i, warm_len in enumerate((122, 64, 16, 4)):
            one_request(list((np.arange(5000 + i * 300,
                                        5000 + i * 300 + warm_len)
                              % 500) + 1), f"warm{i}")
        # Warm the onboard scatter jit too (pow2-bucketed sizes): write
        # zeros to the scratch page — harmless, page 0 is reserved.
        q = sched.run_in_step(lambda: runner.scatter_pages(
            np.zeros(32, np.int32),
            np.zeros((32,) + tuple(kvbm.layout.block_shape),
                     np.dtype(kvbm.layout.dtype))))
        q.get(timeout=60)
        cold = one_request(prefix + [130, 131], "cold")
        # same prefix again: G1 radix prefix-cache hit
        g1_hit = one_request(prefix + [140, 141], "g1hit")
        # flush offloads, then force G1 eviction by filling the pool with
        # unrelated prompts; the prefix blocks survive only in G2
        kvbm.flush(30.0)
        filler = 0
        for i in range(4):
            one_request(list(np.arange(1000 + i * 200,
                                       1000 + i * 200 + 120) % 500
                             + 1), f"fill{i}")
            filler += 1
        g2_onboard = one_request(prefix + [150, 151], "g2")
        onboarded = sched.stats.kvbm_onboarded_blocks
    finally:
        sched.stop()
        kvbm.close()
    return {
        "cold_ttft_ms": round(cold, 2),
        "g1_prefix_hit_ttft_ms": round(g1_hit, 2),
        "g2_onboard_ttft_ms": round(g2_onboard, 2),
        "g2_onboarded_blocks": int(onboarded),
        "g1_speedup_vs_cold": round(cold / max(g1_hit, 1e-9), 2),
        "g2_speedup_vs_cold": round(cold / max(g2_onboard, 1e-9), 2),
    }


def render_markdown(results: dict) -> str:
    lines = ["# BENCH_MULTI — multi-config benchmarks",
             "",
             f"Generated by scripts/bench_multi.py; CPU tiers (mocker "
             f"simulation + tiny real engine), A/B-relative numbers. "
             f"Single-chip absolute throughput lives in bench.py/"
             f"BENCH_r*.json.",
             "",
             "## Router A/B (8 workers, kv vs round-robin)",
             "",
             "| prefix ratio | policy | TTFT p50 (ms) | TTFT p99 | "
             "ITL p50 | ITL p99 | kv TTFT speedup |",
             "|---|---|---|---|---|---|---|"]
    for key, row in results["router_ab"].items():
        ratio = key.split("_")[1]
        for policy in ("round_robin", "kv"):
            s = row[policy]
            speed = (f'{row["kv_ttft_speedup_p50"]}x'
                     if policy == "kv" else "")
            lines.append(
                f"| {ratio} | {policy} | {s['ttft_ms']['p50']} | "
                f"{s['ttft_ms']['p99']} | {s['itl_ms']['p50']} | "
                f"{s['itl_ms']['p99']} | {speed} |")
    lines += ["", "## Aggregated vs disaggregated (offline replay)", "",
              "| mode | TTFT p50 | TTFT p99 | ITL p50 | ITL p99 | "
              "tokens/s |", "|---|---|---|---|---|---|"]
    for mode in ("agg", "disagg"):
        s = results["disagg"][mode]
        lines.append(
            f"| {mode} | {s['ttft_ms']['p50']} | {s['ttft_ms']['p99']} | "
            f"{s['itl_ms']['p50']} | {s['itl_ms']['p99']} | "
            f"{s['tokens_per_s']} |")
    lines.append(
        f"\ndisagg ITL p99 improvement: "
        f"{results['disagg']['disagg_itl_p99_improvement']}x")
    k = results["kvbm_ttft"]
    lines += ["", "## KVBM offload TTFT (real engine, shared 120-token "
              "prefix)", "",
              "| path | TTFT (ms) | speedup vs cold |", "|---|---|---|",
              f"| cold prefill | {k['cold_ttft_ms']} | 1.0x |",
              f"| G1 prefix-cache hit | {k['g1_prefix_hit_ttft_ms']} | "
              f"{k['g1_speedup_vs_cold']}x |",
              f"| G2 host-tier onboard | {k['g2_onboard_ttft_ms']} | "
              f"{k['g2_speedup_vs_cold']}x |",
              f"\nG2 onboarded blocks: {k['g2_onboarded_blocks']}"]
    return "\n".join(lines) + "\n"


def main() -> None:
    parser = argparse.ArgumentParser("bench_multi")
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--out", default="BENCH_MULTI.json")
    parser.add_argument("--md", default="BENCH_MULTI.md")
    args = parser.parse_args()

    results = {}
    t0 = time.time()
    print("router A/B ...", flush=True)
    results["router_ab"] = bench_router_ab(args.quick)
    print("disagg vs agg ...", flush=True)
    results["disagg"] = bench_disagg(args.quick)
    print("kvbm ttft curve ...", flush=True)
    results["kvbm_ttft"] = bench_kvbm_ttft(args.quick)
    results["wall_s"] = round(time.time() - t0, 1)

    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    with open(args.md, "w") as f:
        f.write(render_markdown(results))
    print(json.dumps({"router_kv_speedup_p50@0.9":
                      results["router_ab"]["prefix_0.9"]
                      ["kv_ttft_speedup_p50"],
                      "disagg_itl_p99_improvement":
                      results["disagg"]["disagg_itl_p99_improvement"],
                      "kvbm_g2_speedup":
                      results["kvbm_ttft"]["g2_speedup_vs_cold"],
                      "out": args.out}))


if __name__ == "__main__":
    main()
