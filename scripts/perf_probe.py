"""Decode per-step timing on the attached chip via paired scan lengths.

Runs decode_multi blocks of K=16 and K=128 steps and reports the slope
((t128 - t16) / 112) — per-step device time free of the tunnel RTT (see
perf_common.py for why block_until_ready can't be trusted here).
Component-level attribution lives in perf_components.py.

Run:  python scripts/perf_probe.py [batch] [width_pages]
"""

from __future__ import annotations

import sys
import time

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
sys.path.insert(0, "scripts")

from perf_common import measure_rtt

from dynamo_tpu.engine import ModelRunner, RunnerConfig
from dynamo_tpu.models import get_config
from dynamo_tpu.parallel import MeshConfig, make_mesh

MODEL = "qwen3-0.6b"
BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 8
WIDTH = int(sys.argv[2]) if len(sys.argv) > 2 else 32  # pages per seq
PAGE_SIZE = 16
NUM_PAGES = max(1024, BATCH * WIDTH + 8)


def main():
    cfg = get_config(MODEL)
    runner = ModelRunner(
        cfg,
        RunnerConfig(page_size=PAGE_SIZE, num_pages=NUM_PAGES,
                     max_batch=BATCH, max_pages_per_seq=WIDTH,
                     prefill_buckets=(256,)),
        make_mesh(MeshConfig()), seed=0,
    )
    params = runner.params
    tables = np.zeros((BATCH, WIDTH), np.int32)
    nxt = 1
    for b in range(BATCH):
        tables[b] = np.arange(nxt, nxt + WIDTH)
        nxt += WIDTH
    tables_j = jnp.asarray(tables)
    kv_lens = jnp.full((BATCH,), WIDTH * PAGE_SIZE - 8, jnp.int32)
    tokens = jnp.zeros((BATCH,), jnp.int32)
    positions = kv_lens - 1
    active = jnp.ones((BATCH,), bool)
    temp = jnp.zeros((BATCH,), jnp.float32)
    top_p = jnp.ones((BATCH,), jnp.float32)
    top_k = jnp.zeros((BATCH,), jnp.int32)
    seeds = jnp.zeros((BATCH,), jnp.uint32)
    steps = jnp.zeros((BATCH,), jnp.int32)

    rtt = measure_rtt()
    print(f"tunnel RTT {rtt:.1f} ms", flush=True)

    def block_time(k, n=6):
        fn = runner._build_decode_multi(k)
        state = {"kv": runner.kv_cache}

        def call():
            out_kv, toks = fn(params, state["kv"], tokens, positions,
                              tables_j, kv_lens, active, temp, top_p,
                              top_k, seeds, steps)
            state["kv"] = out_kv
            np.asarray(toks)

        call()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(n):
            call()
        runner.kv_cache = state["kv"]
        return (time.perf_counter() - t0) / n * 1e3

    t16 = block_time(16)
    print(f"decode_multi k=16 block: {t16:.1f} ms", flush=True)
    t128 = block_time(128)
    per_step = (t128 - t16) / 112
    print(f"decode_multi k=128 block: {t128:.1f} ms -> per-step slope "
          f"{per_step:.3f} ms", flush=True)

    wbytes = sum(x.size * x.dtype.itemsize
                 for x in __import__("jax").tree.leaves(params))
    print(f"params {wbytes/1e9:.3f} GB -> {wbytes/819e9*1e3:.2f} ms/step "
          f"weight-stream floor", flush=True)


if __name__ == "__main__":
    main()
