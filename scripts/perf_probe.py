"""Decode-step timing breakdown on the attached chip.

Times isolated jitted pieces of the decode step (bench.py shapes) so the
~X ms/step gap to the HBM roofline can be attributed:

  full      decode_multi block (what bench.py measures), per step
  noattn    forward minus attention (weights stream + sampler + scatter)
  attn      28x paged_attention_decode_xla alone
  gather    the raw KV page gather alone (no math)
  lmhead    final norm + logits matmul alone
  sampler   sample() alone
  scatter   write_kv_stack alone

Run:  python scripts/perf_probe.py [batch] [width_pages]
"""

from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from dynamo_tpu.engine import ModelRunner, RunnerConfig
from dynamo_tpu.engine.sampler import sample
from dynamo_tpu.models import get_config
from dynamo_tpu.models.transformer import (
    forward_decode,
    paged_attention_decode_xla,
    rms_norm,
    write_kv_stack,
)
from dynamo_tpu.parallel import MeshConfig, make_mesh

MODEL = "qwen3-0.6b"
BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 8
WIDTH = int(sys.argv[2]) if len(sys.argv) > 2 else 32  # pages per seq
PAGE_SIZE = 16
NUM_PAGES = max(1024, BATCH * WIDTH + 8)


def timeit(fn, *args, n=20, k_steps=1):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n / k_steps
    return dt * 1e3  # ms


def main():
    cfg = get_config(MODEL)
    mesh = make_mesh(MeshConfig())
    runner = ModelRunner(
        cfg,
        RunnerConfig(page_size=PAGE_SIZE, num_pages=NUM_PAGES,
                     max_batch=BATCH, max_pages_per_seq=WIDTH,
                     prefill_buckets=(256,)),
        mesh, seed=0,
    )
    params, kv = runner.params, runner.kv_cache
    rng = np.random.default_rng(0)
    tables = np.zeros((BATCH, WIDTH), np.int32)
    nxt = 1
    for b in range(BATCH):
        tables[b] = np.arange(nxt, nxt + WIDTH)
        nxt += WIDTH
    tables_j = jnp.asarray(tables)
    kv_lens = jnp.full((BATCH,), WIDTH * PAGE_SIZE - 8, jnp.int32)
    tokens = jnp.zeros((BATCH,), jnp.int32)
    positions = kv_lens - 1
    active = jnp.ones((BATCH,), bool)
    temp = jnp.zeros((BATCH,), jnp.float32)
    top_p = jnp.ones((BATCH,), jnp.float32)
    top_k = jnp.zeros((BATCH,), jnp.int32)
    seeds = jnp.zeros((BATCH,), jnp.uint32)
    steps = jnp.zeros((BATCH,), jnp.int32)

    results = {}

    # full fused block of K steps (bench path)
    K = 16
    fn = runner._build_decode_multi(K)
    full = lambda kv: fn(params, kv, tokens, positions, tables_j, kv_lens,
                         active, temp, top_p, top_k, seeds, steps)[0]
    # kv donated: re-feed output
    out = full(kv)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    N = 8
    for _ in range(N):
        out = full(out)
    jax.block_until_ready(out)
    results["full"] = (time.perf_counter() - t0) / N / K * 1e3
    kv = out

    # single-step decode fn without sampling vs with
    @jax.jit
    def fwd_only(kv, tokens):
        kv2, logits = forward_decode(params, cfg, tokens, positions, kv,
                                     tables_j, kv_lens, active)
        return logits.sum()

    results["fwd_1step"] = timeit(fwd_only, kv, tokens)

    # attention alone: loop over layers on a fixed q
    q = jnp.zeros((BATCH, 1, cfg.n_q_heads, cfg.head_dim), jnp.bfloat16)
    kc = jnp.zeros((BATCH, 1, cfg.n_kv_heads, cfg.head_dim), jnp.bfloat16)

    @jax.jit
    def attn_all(kv, q):
        acc = jnp.zeros((), jnp.float32)
        for layer in range(cfg.n_layers):
            o = paged_attention_decode_xla(q, kv, layer, tables_j, kv_lens,
                                           kc, kc)
            acc += o.astype(jnp.float32).sum()
        return acc

    results["attn_28L"] = timeit(attn_all, kv, q)

    # raw gather alone
    @jax.jit
    def gather_all(kv):
        acc = jnp.zeros((), jnp.float32)
        for layer in range(cfg.n_layers):
            kp = kv[layer, 0][tables_j]
            vp = kv[layer, 1][tables_j]
            acc += kp.astype(jnp.float32).sum() + vp.astype(jnp.float32).sum()
        return acc

    results["gather_28L"] = timeit(gather_all, kv)

    # gather the whole cache contiguously (streaming read bound)
    @jax.jit
    def stream_all(kv):
        return kv.astype(jnp.float32).sum()

    results["stream_pool"] = timeit(stream_all, kv)

    # lm head
    x = jnp.zeros((BATCH, 1, cfg.hidden), jnp.bfloat16)

    @jax.jit
    def lmhead(x):
        h = rms_norm(x, params["final_norm"], cfg.rms_eps)
        head = params["embed"].T
        return jnp.einsum("bth,hv->btv", h, head).astype(jnp.float32).sum()

    results["lmhead"] = timeit(lmhead, x)

    # sampler
    logits = jnp.zeros((BATCH, cfg.vocab_size), jnp.float32)

    @jax.jit
    def samp(logits):
        return sample(logits, temp, top_p, top_k, seeds, steps)

    results["sampler"] = timeit(samp, logits)

    # scatter (write_kv_stack)
    ks = jnp.zeros((cfg.n_layers, BATCH, 1, cfg.n_kv_heads, cfg.head_dim),
                   jnp.bfloat16)

    @jax.jit
    def scat(kv):
        return write_kv_stack(kv, ks, ks, tables_j, positions[:, None],
                              active[:, None])[0, 0, 0, 0, 0, 0]

    # donation-free sum to avoid copying pool: time with .at returning new
    scat2 = jax.jit(
        lambda kv: write_kv_stack(kv, ks, ks, tables_j, positions[:, None],
                                  active[:, None]),
        donate_argnums=(0,))
    out = scat2(kv)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(20):
        out = scat2(out)
    jax.block_until_ready(out)
    results["scatter_donated"] = (time.perf_counter() - t0) / 20 * 1e3

    dev = jax.devices()[0]
    print(f"device={dev.device_kind} batch={BATCH} width={WIDTH}pages "
          f"ctx={WIDTH*PAGE_SIZE}")
    wbytes = sum(x.size * x.dtype.itemsize
                 for x in jax.tree.leaves(params))
    print(f"param bytes: {wbytes/1e9:.3f} GB -> roofline "
          f"{wbytes/819e9*1e3:.2f} ms/step (weights only)")
    for k, v in results.items():
        print(f"{k:16s} {v:8.3f} ms")


if __name__ == "__main__":
    main()
