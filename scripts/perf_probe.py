"""Decode-step timing breakdown on the attached chip.

The chip is tunnel-attached: `jax.block_until_ready` does NOT synchronize
(returns immediately) and every host readback costs ~50-100ms RTT. So every
measurement here (a) forces a small host readback per call and (b) subtracts
the measured RTT; per-step decode additionally uses paired scan lengths
(K=16 vs K=128) so the per-step slope is RTT-free.

Run:  python scripts/perf_probe.py [batch] [width_pages]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from dynamo_tpu.engine import ModelRunner, RunnerConfig
from dynamo_tpu.engine.sampler import sample
from dynamo_tpu.models import get_config
from dynamo_tpu.models.transformer import (
    forward_decode,
    paged_attention_decode_xla,
    rms_norm,
    write_kv_stack,
)
from dynamo_tpu.parallel import MeshConfig, make_mesh

MODEL = "qwen3-0.6b"
BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 8
WIDTH = int(sys.argv[2]) if len(sys.argv) > 2 else 32  # pages per seq
PAGE_SIZE = 16
NUM_PAGES = max(1024, BATCH * WIDTH + 8)

RTT_MS = 0.0


def measure_rtt() -> float:
    @jax.jit
    def tiny(x):
        return x + 1

    x = jnp.zeros((), jnp.float32)
    float(tiny(x))
    t0 = time.perf_counter()
    n = 20
    for _ in range(n):
        float(tiny(x))
    return (time.perf_counter() - t0) / n * 1e3


def timeit(fn, *args, n=10):
    """fn must return a SCALAR (or tiny) array; we read it back per call to
    force synchronization, then subtract the tunnel RTT."""
    np.asarray(fn(*args))  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(n):
        np.asarray(fn(*args))
    dt = (time.perf_counter() - t0) / n * 1e3
    return max(dt - RTT_MS, 0.0)


def main():
    global RTT_MS
    cfg = get_config(MODEL)
    mesh = make_mesh(MeshConfig())
    runner = ModelRunner(
        cfg,
        RunnerConfig(page_size=PAGE_SIZE, num_pages=NUM_PAGES,
                     max_batch=BATCH, max_pages_per_seq=WIDTH,
                     prefill_buckets=(256,)),
        mesh, seed=0,
    )
    params, kv = runner.params, runner.kv_cache
    tables = np.zeros((BATCH, WIDTH), np.int32)
    nxt = 1
    for b in range(BATCH):
        tables[b] = np.arange(nxt, nxt + WIDTH)
        nxt += WIDTH
    tables_j = jnp.asarray(tables)
    kv_lens = jnp.full((BATCH,), WIDTH * PAGE_SIZE - 8, jnp.int32)
    tokens = jnp.zeros((BATCH,), jnp.int32)
    positions = kv_lens - 1
    active = jnp.ones((BATCH,), bool)
    temp = jnp.zeros((BATCH,), jnp.float32)
    top_p = jnp.ones((BATCH,), jnp.float32)
    top_k = jnp.zeros((BATCH,), jnp.int32)
    seeds = jnp.zeros((BATCH,), jnp.uint32)
    steps = jnp.zeros((BATCH,), jnp.int32)

    RTT_MS = measure_rtt()
    print(f"tunnel RTT {RTT_MS:.1f} ms (subtracted from all numbers)",
          flush=True)

    # -- decode per-step via paired scan lengths (RTT-free slope) ----------
    def block_time(k, n=6):
        fn = runner._build_decode_multi(k)
        state = {"kv": runner.kv_cache}

        def call():
            out_kv, toks = fn(params, state["kv"], tokens, positions,
                              tables_j, kv_lens, active, temp, top_p,
                              top_k, seeds, steps)
            state["kv"] = out_kv
            np.asarray(toks)

        call()  # compile + warm
        t0 = time.perf_counter()
        for _ in range(n):
            call()
        runner.kv_cache = state["kv"]
        return (time.perf_counter() - t0) / n * 1e3

    t16 = block_time(16)
    print(f"decode_multi k=16 block: {t16:.1f} ms "
          f"({(t16 - RTT_MS) / 16:.2f} ms/step naive)", flush=True)
    t128 = block_time(128)
    per_step = (t128 - t16) / 112
    print(f"decode_multi k=128 block: {t128:.1f} ms -> per-step slope "
          f"{per_step:.3f} ms", flush=True)

    kv = runner.kv_cache
    results = {}

    # single full decode step (forward only, no sampling)
    @jax.jit
    def fwd_only(kv, tokens):
        _, logits = forward_decode(params, cfg, tokens, positions, kv,
                                   tables_j, kv_lens, active)
        return logits.sum()

    results["fwd_1step"] = timeit(fwd_only, kv, tokens)
    print(f"fwd_1step {results['fwd_1step']:.3f} ms", flush=True)

    # attention alone over all layers
    q = jnp.zeros((BATCH, 1, cfg.n_q_heads, cfg.head_dim), jnp.bfloat16)
    kc = jnp.zeros((BATCH, 1, cfg.n_kv_heads, cfg.head_dim), jnp.bfloat16)

    @jax.jit
    def attn_all(kv, q):
        acc = jnp.zeros((), jnp.float32)
        for layer in range(cfg.n_layers):
            o = paged_attention_decode_xla(q, kv, layer, tables_j, kv_lens,
                                           kc, kc)
            acc += o.astype(jnp.float32).sum()
        return acc

    results["attn_28L"] = timeit(attn_all, kv, q)
    print(f"attn_28L {results['attn_28L']:.3f} ms", flush=True)

    # raw KV page gather alone
    @jax.jit
    def gather_all(kv):
        acc = jnp.zeros((), jnp.float32)
        for layer in range(cfg.n_layers):
            acc += kv[layer, 0][tables_j].astype(jnp.float32).sum()
            acc += kv[layer, 1][tables_j].astype(jnp.float32).sum()
        return acc

    results["gather_28L"] = timeit(gather_all, kv)
    print(f"gather_28L {results['gather_28L']:.3f} ms", flush=True)

    # stream the whole pool contiguously (bandwidth reference)
    @jax.jit
    def stream_all(kv):
        return kv.astype(jnp.float32).sum()

    results["stream_pool"] = timeit(stream_all, kv)
    print(f"stream_pool {results['stream_pool']:.3f} ms "
          f"(pool {kv.size * 2 / 1e9:.2f} GB)", flush=True)

    # lm head matmul
    x = jnp.zeros((BATCH, 1, cfg.hidden), jnp.bfloat16)

    @jax.jit
    def lmhead(x):
        h = rms_norm(x, params["final_norm"], cfg.rms_eps)
        return jnp.einsum("bth,hv->btv", h,
                          params["embed"].T).astype(jnp.float32).sum()

    results["lmhead"] = timeit(lmhead, x)
    print(f"lmhead {results['lmhead']:.3f} ms", flush=True)

    # sampler
    logits = jnp.zeros((BATCH, cfg.vocab_size), jnp.float32)

    @jax.jit
    def samp(logits):
        return sample(logits, temp, top_p, top_k, seeds, steps).sum()

    results["sampler"] = timeit(samp, logits)
    print(f"sampler {results['sampler']:.3f} ms", flush=True)

    # deferred KV write (2 batched scatters)
    ks = jnp.zeros((cfg.n_layers, BATCH, 1, cfg.n_kv_heads, cfg.head_dim),
                   jnp.bfloat16)

    state = {"kv": kv}
    scat = jax.jit(
        lambda kv: write_kv_stack(kv, ks, ks, tables_j, positions[:, None],
                                  active[:, None]),
        donate_argnums=(0,))

    def scat_call():
        out = scat(state["kv"])
        state["kv"] = out
        np.asarray(out[0, 0, 0, 0, 0, 0])

    scat_call()
    t0 = time.perf_counter()
    for _ in range(10):
        scat_call()
    results["scatter"] = max((time.perf_counter() - t0) / 10 * 1e3 - RTT_MS,
                             0.0)
    print(f"scatter {results['scatter']:.3f} ms", flush=True)

    dev = jax.devices()[0]
    print(f"device={dev.device_kind} batch={BATCH} width={WIDTH}pages "
          f"ctx={WIDTH*PAGE_SIZE}")
    wbytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
    print(f"param bytes: {wbytes/1e9:.3f} GB -> roofline "
          f"{wbytes/819e9*1e3:.2f} ms/step (weights only)")


if __name__ == "__main__":
    main()
