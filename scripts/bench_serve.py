"""Served-path throughput bench: the REAL scheduler + paged pool +
(optionally) an active KVBM host tier on the real chip — the
steady-state serving number, not the raw-runner number bench.py owns.

N concurrent requests (ISL/OSL configurable) flow through
InferenceScheduler with continuous batching; with --kvbm-host-blocks
the offload worker runs DURING decode (the 'KVBM offload active'
configuration BASELINE.json's north star describes), so the number
includes any offload interference.

Usage:
  python scripts/bench_serve.py --model mistral-7b --batch 4 \
      --num-pages 256 --requests 12 --isl 256 --osl 64 \
      --kvbm-host-blocks 1024
"""

from __future__ import annotations

import argparse
import json
import os
import queue as thread_queue
import sys
import threading
import time
import uuid

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser("bench_serve")
    parser.add_argument("--model", default="qwen3-0.6b")
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--page-size", type=int, default=16)
    parser.add_argument("--num-pages", type=int, default=1024)
    parser.add_argument("--max-pages-per-seq", type=int, default=64)
    parser.add_argument("--requests", type=int, default=24)
    parser.add_argument("--isl", type=int, default=256)
    parser.add_argument("--osl", type=int, default=64)
    parser.add_argument("--kv-dtype", default="model")
    parser.add_argument("--weight-dtype", default="model")
    parser.add_argument("--kvbm-host-blocks", type=int, default=0)
    args = parser.parse_args()

    from dynamo_tpu.engine import InferenceScheduler, ModelRunner, RunnerConfig
    from dynamo_tpu.llm.protocols import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models import get_config
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    config = get_config(args.model)
    runner = ModelRunner(
        config,
        RunnerConfig(page_size=args.page_size, num_pages=args.num_pages,
                     max_batch=args.batch,
                     max_pages_per_seq=args.max_pages_per_seq,
                     prefill_buckets=(256,), kv_dtype=args.kv_dtype,
                     weight_dtype=args.weight_dtype),
        make_mesh(MeshConfig()), seed=0)
    kvbm = None
    if args.kvbm_host_blocks:
        from dynamo_tpu.block_manager import (
            BlockLayoutSpec,
            KvbmConfig,
            KvBlockManager,
        )

        kvbm = KvBlockManager(
            KvbmConfig(host_blocks=args.kvbm_host_blocks, offload_batch=8),
            BlockLayoutSpec.from_runner_layout(runner.kv_layout()))
    sched = InferenceScheduler(runner, kvbm=kvbm)
    sched.start()

    rng = np.random.default_rng(0)
    done: thread_queue.Queue = thread_queue.Queue()
    tokens_out = [0]
    lock = threading.Lock()

    def submit(i: int) -> None:
        prompt = rng.integers(2, config.vocab_size - 2,
                              args.isl).astype(np.int32).tolist()

        def emit(out) -> None:
            with lock:
                tokens_out[0] += len(out.token_ids)
            if out.finish_reason is not None:
                done.put((i, out.finish_reason, out.error))

        sched.submit(PreprocessedRequest(
            request_id=uuid.uuid4().hex, token_ids=prompt,
            sampling=SamplingOptions(max_tokens=args.osl, temperature=0.0),
            stop=StopConditions(ignore_eos=True)), emit)

    try:
        # Warmup: one full request compiles prefill + decode. A failed
        # warmup (capacity rejection etc.) would silently bill the first
        # measured request for compilation — assert it succeeded.
        submit(-1)
        _i, reason, err = done.get(timeout=1200)
        assert err is None and reason == "length", (reason, err)
        with lock:
            tokens_out[0] = 0
        # Reset scheduler stats too: the reported sched block must cover
        # exactly the measured requests, like the token counters beside
        # it.
        from dynamo_tpu.engine.scheduler import SchedulerStats

        sched.stats = SchedulerStats()
        t0 = time.perf_counter()
        for i in range(args.requests):
            submit(i)
        finished = 0
        while finished < args.requests:
            idx, reason, err = done.get(timeout=1200)
            assert err is None, err
            finished += 1
        elapsed = time.perf_counter() - t0
        out_toks = tokens_out[0]
        result = {
            "metric": (f"served decode throughput {args.model} "
                       f"kv={args.kv_dtype} w={args.weight_dtype} "
                       f"batch<={args.batch} "
                       f"isl={args.isl} osl={args.osl}"
                       + (f" kvbm_g2={args.kvbm_host_blocks}"
                          if args.kvbm_host_blocks else "")),
            "requests": args.requests,
            "output_tokens": out_toks,
            "output_tokens_per_sec": round(out_toks / elapsed, 1),
            "total_tokens_per_sec": round(
                args.requests * (args.isl + args.osl) / elapsed, 1),
            "wall_s": round(elapsed, 2),
            "sched": {
                "iterations": sched.stats.steps,
                "decode_tokens": sched.stats.decode_tokens,
                "prefill_tokens": sched.stats.prefill_tokens,
                "fused_with_prefill": sched.stats.fused_steps_with_prefill,
                "admitted_during_inflight":
                    sched.stats.admitted_during_inflight,
            },
        }
        if kvbm is not None:
            kvbm.flush(60.0)
            result["kvbm"] = kvbm.usage()
        print(json.dumps(result), flush=True)
    finally:
        sched.stop()
        if kvbm is not None:
            kvbm.close()


if __name__ == "__main__":
    main()
