"""Decode-wall probe: where do the non-roofline 40% go?

Times steady-state fused decode blocks under controlled variations:
  * ctx ~0 (weights-only floor) vs ctx=256 -> attention+KV share
  * Pallas pool kernel vs XLA gather path
  * batch 8 vs 16 vs 32
  * pages_per_chunk sweep for the pool kernel

Prints one JSON line per config. Run on the real chip. (VERDICT r3 task 5:
'profile where the remaining 40% goes'.)"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_config(label, bs, ctx, attention, ppc=None, block=64, n_blocks=4):
    os.environ["DYNT_ATTENTION"] = attention
    if ppc is not None:
        os.environ["DYNT_PALLAS_PPC"] = str(ppc)
    else:
        os.environ.pop("DYNT_PALLAS_PPC", None)

    import jax

    from dynamo_tpu.engine.model_runner import (
        ModelRunner,
        RunnerConfig,
        bucket_table_width,
    )
    from dynamo_tpu.models import get_config
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    config = get_config("qwen3-0.6b")
    page_size = 16
    max_pages = 64
    runner = ModelRunner(
        config,
        RunnerConfig(page_size=page_size, num_pages=2048, max_batch=bs,
                     max_pages_per_seq=max_pages, prefill_buckets=(256,)),
        make_mesh(MeshConfig()),
        seed=0,
    )
    total = ctx + (n_blocks + 1) * block
    pages_per_seq = total // page_size + 1
    tables = np.zeros((bs, max_pages), np.int32)
    rng = np.random.default_rng(0)
    nxt = 1
    for b in range(bs):
        tables[b, :pages_per_seq] = np.arange(nxt, nxt + pages_per_seq)
        nxt += pages_per_seq
        if ctx:
            prompt = rng.integers(0, config.vocab_size, ctx).astype(np.int32)
            runner.prefill_chunk(prompt, 0, tables[b], ctx, (0.0, 1.0, 0, 0))

    width = bucket_table_width(pages_per_seq, max_pages)
    btables = np.ascontiguousarray(tables[:, :width])
    positions = np.full(bs, ctx, np.int32)
    kv_lens = np.full(bs, ctx + 1, np.int32)
    state = {"tokens": np.zeros(bs, np.int32), "pending": None}
    steps_np = np.zeros(bs, np.int32)

    def step_block():
        nonlocal positions, kv_lens, steps_np
        toks = runner.decode_multi(
            state["tokens"], positions, btables, kv_lens,
            np.ones(bs, bool), np.zeros(bs, np.float32),
            np.ones(bs, np.float32), np.zeros(bs, np.int32),
            np.zeros(bs, np.uint32), steps_np, k=block, return_device=True)
        if state["pending"] is not None:
            np.asarray(state["pending"])
        state["pending"] = toks
        state["tokens"] = toks[-1]
        positions += block
        kv_lens += block
        steps_np += block

    def drain():
        if state["pending"] is not None:
            np.asarray(state["pending"])
            state["pending"] = None

    step_block()
    drain()
    trials = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n_blocks):
            step_block()
        drain()
        trials.append(time.perf_counter() - t0)
        positions -= n_blocks * block
        kv_lens -= n_blocks * block
        steps_np -= n_blocks * block
    best = sorted(trials)[1]
    tok_s = bs * n_blocks * block / best
    print(json.dumps({"label": label, "bs": bs, "ctx": ctx,
                      "attention": attention, "ppc": ppc,
                      "tok_per_sec": round(tok_s, 1),
                      "steps_per_sec": round(tok_s / bs, 1),
                      "us_per_step": round(1e6 * best / (n_blocks * block),
                                           1)}), flush=True)


CONFIGS = [
    ("floor-bs8", 8, 0, "pallas"),
    ("base-bs8", 8, 256, "pallas"),
    ("xla-bs8", 8, 256, "xla"),
    ("floor-bs16", 16, 0, "pallas"),
    ("base-bs16", 16, 256, "pallas"),
    ("floor-bs32", 32, 0, "pallas"),
    ("base-bs32", 32, 256, "pallas"),
]


def main():
    import gc

    which = sys.argv[1] if len(sys.argv) > 1 else None
    for cfg in CONFIGS:
        if which and cfg[0] != which:
            continue
        run_config(*cfg)
        gc.collect()  # free the previous runner's HBM before the next


if __name__ == "__main__":
    main()
