#!/usr/bin/env python
"""Offline W4A16 pack-layout migration (docs/quantization.md).

Operates on an `.npz` dump of a quantized param pytree with flattened
path keys (`layers/0/wq/q4`, `layers/0/wq/qs4`, ... — any prefix works;
every `<prefix>/q4` must have `<prefix>/qs4` + `<prefix>/qz4`
siblings). Every packed leaf is migrated to the target layout with
scale/zero rows untouched; the code transform is a nibble bijection so
`--to v2` then `--to v1` restores the input bit-for-bit.

The serving path does NOT need this: ModelRunner transparently repacks
a mismatched tree at load (engine/model_runner.py). This tool is for
migrating stored weight-service snapshots once, so fleets skip the
per-boot host repack.

Usage:
  python scripts/q4_repack.py in.npz out.npz [--to auto|v1|v2]
  python scripts/q4_repack.py in.npz --report   # per-leaf versions
"""

from __future__ import annotations

import argparse
import pathlib
import sys

import numpy as np

# Runnable as `python scripts/q4_repack.py` from the repo root.
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))


def repack_npz(src: dict, to: str) -> tuple[dict, list[tuple[str, int, int]]]:
    """Returns (new arrays dict, [(prefix, from_version, to_version)])."""
    from dynamo_tpu.ops.q4_linear import (
        PACK_V1,
        PACK_V2,
        pack_version,
        repack_q4_leaf,
    )

    version = {"auto": None, "v1": PACK_V1, "v2": PACK_V2}[to]
    out = dict(src)
    moved: list[tuple[str, int, int]] = []
    for key in sorted(src):
        if key != "q4" and not (key.endswith("/q4")
                                or key.endswith(".q4")):
            continue
        prefix = key[: -len("q4")]
        try:
            leaf = {"q4": src[key], "qs4": src[prefix + "qs4"],
                    "qz4": src[prefix + "qz4"]}
        except KeyError as exc:
            raise SystemExit(
                f"{key}: missing scale/zero sibling {exc}") from exc
        new = repack_q4_leaf(leaf, version)
        cur = pack_version(np.asarray(leaf["q4"]))
        now = pack_version(np.asarray(new["q4"]))
        if new is not leaf:
            out[key] = np.asarray(new["q4"])
        moved.append((prefix.rstrip("/.") or key, cur, now))
    return out, moved


def main() -> int:
    parser = argparse.ArgumentParser("q4_repack")
    parser.add_argument("src")
    parser.add_argument("dst", nargs="?")
    parser.add_argument("--to", default="auto",
                        choices=("auto", "v1", "v2"),
                        help="target layout (auto = DYNT_Q4_VARIANT "
                             "policy: v2 wherever well-formed)")
    parser.add_argument("--report", action="store_true",
                        help="print per-leaf layout versions, write "
                             "nothing")
    args = parser.parse_args()

    with np.load(args.src) as f:
        src = {k: f[k] for k in f.files}
    out, moved = repack_npz(src, args.to)
    if not moved:
        print(f"{args.src}: no packed-int4 leaves found", file=sys.stderr)
        return 1
    for prefix, cur, now in moved:
        tag = f"v{cur}" if cur == now else f"v{cur} -> v{now}"
        print(f"  {prefix}: {tag}")
    if args.report:
        return 0
    if not args.dst:
        print("dst required unless --report", file=sys.stderr)
        return 2
    np.savez(args.dst, **out)
    changed = sum(1 for _, c, n in moved if c != n)
    print(f"wrote {args.dst}: {changed}/{len(moved)} leaves repacked")
    return 0


if __name__ == "__main__":
    sys.exit(main())
