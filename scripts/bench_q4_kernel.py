"""Microbench: q4_matmul vs q8_matmul vs bf16 XLA matmul on one chip.

Times a single [M, K] x [K, N] projection-shaped matmul per variant and
prints GB/s of weight traffic achieved (the kernels are weight-stream
bound at decode M). Used to tune the W4A16 kernel's block shapes.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.ops.q4_linear import q4_matmul, quantize_weight_q4
from dynamo_tpu.ops.q8_linear import q8_matmul, quantize_weight


INNER = 32


def timeit(fn, x, *args, n=8):
    """One jitted lax.scan of INNER chained matmuls per trial: the chain
    defeats overlap/dedupe, the scan amortizes dispatch overhead."""
    k = x.shape[1]

    @jax.jit
    def trial(xc):
        def body(c, _):
            out = fn(c, *args)
            return c + out[:, :k].astype(c.dtype) * 1e-6, ()

        return jax.lax.scan(body, xc, (), length=INNER)[0]

    xc = trial(x)
    jax.block_until_ready(xc)
    t0 = time.perf_counter()
    for _ in range(n):
        xc = trial(xc)
    jax.block_until_ready(xc)
    return (time.perf_counter() - t0) / (n * INNER)


def main():
    m, k, n = 16, 4096, 14336
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((m, k)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((k, n)), jnp.float32)
    wb = w.astype(jnp.bfloat16)
    q8 = quantize_weight(w, 1)
    q4 = quantize_weight_q4(w, 1)
    q8 = jax.device_put(q8)
    q4 = jax.device_put(q4)

    t_bf = timeit(lambda a, b: a @ b, x, wb)
    t_q8 = timeit(q8_matmul, x, q8["q8"], q8["qs"])
    t_q4 = timeit(q4_matmul, x, q4["q4"], q4["qs4"], q4["qz4"])
    for name, t, byts in (
        ("bf16", t_bf, k * n * 2),
        ("q8", t_q8, k * n),
        ("q4", t_q4, k * n // 2),
    ):
        print(f"{name}: {t * 1e6:9.1f} us  {byts / t / 1e9:7.1f} GB/s "
              f"(weight bytes {byts / 1e6:.1f} MB)")


if __name__ == "__main__":
    main()
