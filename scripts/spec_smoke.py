#!/usr/bin/env python
"""Speculative-decoding smoke: mocker-backed speculative scenario.

CI entrypoint (the `spec-smoke` job): replay a synthetic trace through
the speculative-worker mocker profile
(`tpu-v5e-qwen3-0.6b-spec`, acceptance-rate-parameterized multi-token
steps) next to the plain profile, then assert that

  * the speculative replay reports nonzero proposed/accepted counters
    with a realized acceptance rate in a sane band around the
    configured per-position rate,
  * every request still receives its full output-token budget (the
    multi-token steps never over- or under-emit),
  * the speculative profile's token throughput beats the plain profile
    (the whole point of the plane — FLOPs traded for latency),

and write the acceptance-rate stats JSON as a CI artifact. Exits
nonzero on any violated invariant.

Usage: python scripts/spec_smoke.py [--requests N] [--out DIR]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import pathlib
import sys

# Runnable as `python scripts/spec_smoke.py` from the repo root.
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))


async def run(out_dir: pathlib.Path, requests: int) -> int:
    from dynamo_tpu.mocker.engine import MockerConfig
    from dynamo_tpu.mocker.loadgen import OfflineReplay, synthesize_trace

    records = synthesize_trace(requests, rate_rps=100.0, isl_mean=128,
                               osl_mean=48, seed=7)
    budget = sum(r.osl for r in records)

    spec_cfg = MockerConfig.from_timing_preset(
        "tpu-v5e-qwen3-0.6b-spec", speedup_ratio=50.0)
    plain_cfg = MockerConfig.from_timing_preset(
        "tpu-v5e-qwen3-0.6b", speedup_ratio=50.0)

    spec = (await OfflineReplay(config=spec_cfg).run(records)).summary()
    plain = (await OfflineReplay(config=plain_cfg).run(records)).summary()

    report = {"spec": spec, "plain": plain,
              "configured_acceptance": spec_cfg.spec_acceptance,
              "spec_k": spec_cfg.spec_k}
    out_dir.mkdir(parents=True, exist_ok=True)
    (out_dir / "spec-smoke.json").write_text(json.dumps(report, indent=2))

    failures = []
    stats = spec.get("spec") or {}
    if not stats.get("proposed") or not stats.get("accepted"):
        failures.append(f"no speculation stats in report: {stats}")
    # Realized per-position acceptance compounds through the
    # first-rejection rule: for per-position p and k drafts the expected
    # realized rate is p(1-p^k)/(k(1-p)) — ~0.45 for p=0.7, k=4. Accept
    # a generous band; the assertion is "the model is wired", not a
    # statistics exam.
    rate = stats.get("acceptance_rate", 0.0)
    if not 0.2 <= rate <= 0.8:
        failures.append(f"acceptance rate {rate} outside sane band")
    if spec["errors"] or plain["errors"]:
        failures.append(
            f"errors: spec={spec['errors']} plain={plain['errors']}")
    if spec["output_tokens"] != budget:
        failures.append(
            f"spec replay emitted {spec['output_tokens']} tokens, "
            f"trace budget is {budget}")
    if spec["tokens_per_s"] <= plain["tokens_per_s"]:
        failures.append(
            f"speculative profile is not faster: spec "
            f"{spec['tokens_per_s']} tok/s vs plain "
            f"{plain['tokens_per_s']} tok/s")

    print(json.dumps(report["spec"], indent=2))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print(f"spec-smoke OK: {stats['accepted']}/{stats['proposed']} "
          f"accepted ({rate:.2%}), "
          f"{spec['tokens_per_s']}/{plain['tokens_per_s']} tok/s "
          "spec/plain")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser("spec_smoke")
    parser.add_argument("--requests", type=int, default=32)
    parser.add_argument("--out", default="spec-smoke")
    args = parser.parse_args()
    return asyncio.run(run(pathlib.Path(args.out), args.requests))


if __name__ == "__main__":
    sys.exit(main())
