#!/usr/bin/env python
"""Overlap-plane smoke: pipelined disagg + bandwidth-budgeted offload.

CI entrypoint (the `disagg-smoke` job), CPU/mocker-measurable proof of
the two overlap claims (ISSUE 8 acceptance criteria):

  1. **Pipelined disagg beats serial on TTFT at equal ITL.** Replay one
     trace through the mocker xPyD profile (prefill pool + decode pool,
     measured v5e step physics + a modeled per-block KV handoff cost)
     twice — chunked pipeline on vs off — and assert the pipelined
     replay's TTFT p50 is strictly lower while ITL p50 stays equal
     (the handoff model only ever delays the first token).

  2. **Offload-active decode stays within 20% of offload-idle.** Drive a
     synthetic decode step loop (fixed per-step cost on the step thread,
     gap-window drain between steps — the scheduler's shape) under a
     continuous KVBM offload burst through the real OffloadManager, and
     assert the budgeted manager (DYNT_OFFLOAD_BW_FRAC semantics) keeps
     step throughput >= 80% of the offload-idle rate. The same scenario
     with the budget disabled documents the collapse being prevented.

Writes the scenario report JSON as a CI artifact; exits nonzero on any
violated invariant.

Usage: python scripts/disagg_smoke.py [--requests N] [--out DIR]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import pathlib
import queue as thread_queue
import sys
import threading
import time

# Runnable as `python scripts/disagg_smoke.py` from the repo root.
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))


async def disagg_scenario(requests: int) -> dict:
    from dynamo_tpu.mocker.engine import MockerConfig
    from dynamo_tpu.mocker.loadgen import OfflineReplay, synthesize_trace

    # Long prompts + moderate speedup keep the modeled handoff delta an
    # order of magnitude above asyncio timer jitter, and the arrival
    # rate sits below the prefill pool's service rate so queueing noise
    # doesn't swamp the p50 (same operating point as bench.py's
    # bench_disagg_point).
    records = synthesize_trace(requests, rate_rps=5.0, isl_mean=4096,
                               osl_mean=32, seed=11)
    budget = sum(r.osl for r in records)
    cfg = MockerConfig.from_timing_preset(
        "tpu-v5e-qwen3-0.6b", speedup_ratio=10.0,
        max_prefill_tokens_per_step=512,  # long prompts -> real chunking
        # Cross-host DCN relay (~1 GB/s) rather than the preset's
        # same-host 4.5 GB/s: the conservative inter-slice operating
        # point, and it keeps the asserted TTFT gap an order of
        # magnitude above replay scheduling noise.
        kv_transfer_us_per_block=2000.0)

    async def run(pipeline: bool) -> dict:
        replay = OfflineReplay(mode="disagg", num_workers=2,
                               num_prefill_workers=2,
                               config=cfg, disagg_pipeline=pipeline)
        return (await replay.run(records)).summary()

    pipelined = await run(True)
    serial = await run(False)
    return {"pipelined": pipelined, "serial": serial,
            "trace_output_tokens": budget,
            "kv_transfer_us_per_block": cfg.kv_transfer_us_per_block}


def offload_scenario(*, bw_frac: float, blocks: int = 48,
                     step_ms: float = 4.0, gather_ms: float = 2.0,
                     duration_s: float = 2.0) -> dict:
    """Synthetic serving loop: the 'scheduler' thread runs fixed-cost
    decode steps and drains submitted gather closures between them (the
    run_in_gap shape); the OffloadManager feeds it a continuous store
    burst. Steps/sec with the burst active vs idle measures exactly the
    step-time the offload path steals."""
    from dynamo_tpu.block_manager.offload import OffloadManager

    gap_q: thread_queue.Queue = thread_queue.Queue()
    stop = threading.Event()
    steps = {"n": 0}

    def step_loop() -> None:
        while not stop.is_set():
            time.sleep(step_ms / 1e3)  # the decode step (device busy)
            steps["n"] += 1
            while True:  # gap drain
                try:
                    fn = gap_q.get_nowait()
                except thread_queue.Empty:
                    break
                fn()

    def run_in_gap(fn):
        out: thread_queue.Queue = thread_queue.Queue(1)

        def wrapped():
            try:
                out.put((fn(), None))
            except Exception as exc:  # noqa: BLE001
                out.put((None, exc))

        gap_q.put(wrapped)
        return out

    def gather(ids):
        time.sleep(gather_ms / 1e3)  # modeled device-gather cost in-step
        return [0] * len(ids)

    # Idle rate first.
    thread = threading.Thread(target=step_loop, daemon=True)
    thread.start()
    t0 = time.monotonic()
    time.sleep(duration_s / 2)
    idle_rate = steps["n"] / (time.monotonic() - t0)

    mgr = OffloadManager(
        lookup_pages=lambda hs: [1 + (h % 7) for h in hs],
        gather=gather, run_in_step=run_in_gap,
        sink=lambda h, b, p: None,
        batch_size=4, subbatch=2, bw_frac=bw_frac, queue_cap=4096,
    )
    base = steps["n"]
    t1 = time.monotonic()
    seq = 0
    while time.monotonic() - t1 < duration_s:
        mgr.notify_stored(list(range(seq, seq + blocks)), parent=None)
        seq += blocks
        time.sleep(0.05)
    active_rate = (steps["n"] - base) / (time.monotonic() - t1)
    mgr.close()
    stop.set()
    thread.join(timeout=5)
    return {"bw_frac": bw_frac,
            "idle_steps_per_s": round(idle_rate, 1),
            "active_steps_per_s": round(active_rate, 1),
            "active_vs_idle": round(active_rate / max(idle_rate, 1e-9), 3)}


async def run(out_dir: pathlib.Path, requests: int) -> int:
    disagg = await disagg_scenario(requests)
    offload = offload_scenario(bw_frac=0.2)
    offload_unbudgeted = offload_scenario(bw_frac=0.0)

    report = {"disagg": disagg, "offload": offload,
              "offload_unbudgeted": offload_unbudgeted}
    out_dir.mkdir(parents=True, exist_ok=True)
    (out_dir / "disagg-smoke.json").write_text(json.dumps(report, indent=2))

    failures = []
    pipe, serial = disagg["pipelined"], disagg["serial"]
    if pipe["errors"] or serial["errors"]:
        failures.append(f"replay errors: pipelined={pipe['errors']} "
                        f"serial={serial['errors']}")
    if pipe["output_tokens"] != disagg["trace_output_tokens"]:
        failures.append(
            f"pipelined replay emitted {pipe['output_tokens']} tokens, "
            f"trace budget is {disagg['trace_output_tokens']}")
    if not pipe["ttft_ms"]["p50"] < serial["ttft_ms"]["p50"]:
        failures.append(
            f"pipelined disagg TTFT p50 {pipe['ttft_ms']['p50']}ms is not "
            f"strictly better than serial {serial['ttft_ms']['p50']}ms")
    # "Equal ITL": the handoff model only delays first tokens, so decode
    # cadence must match within a generous scheduling-noise band — 15%
    # relative with a 0.25ms absolute floor (at 50x replay speedup the
    # modeled ITL is sub-ms and asyncio timer jitter dominates below it).
    s_itl = serial["itl_ms"]["p50"]
    if abs(pipe["itl_ms"]["p50"] - s_itl) > max(0.15 * s_itl, 0.25):
        failures.append(
            f"ITL p50 diverged: pipelined {pipe['itl_ms']['p50']}ms vs "
            f"serial {s_itl}ms (not an equal-ITL comparison)")
    if offload["active_vs_idle"] < 0.8:
        failures.append(
            f"budgeted offload-active throughput is "
            f"{offload['active_vs_idle']:.0%} of idle (< 80% target)")

    print(json.dumps(report, indent=2))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    print(f"disagg-smoke OK: TTFT p50 {pipe['ttft_ms']['p50']}ms pipelined "
          f"vs {serial['ttft_ms']['p50']}ms serial at ITL p50 "
          f"{pipe['itl_ms']['p50']}/{s_itl}ms; offload-active decode at "
          f"{offload['active_vs_idle']:.0%} of idle (unbudgeted: "
          f"{offload_unbudgeted['active_vs_idle']:.0%})")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser("disagg_smoke")
    parser.add_argument("--requests", type=int, default=16)
    parser.add_argument("--out", default="disagg-smoke")
    args = parser.parse_args()
    return asyncio.run(run(pathlib.Path(args.out), args.requests))


if __name__ == "__main__":
    sys.exit(main())
