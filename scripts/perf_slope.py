"""Ablation slopes: per-step decode cost of each component, measured as
paired-scan-length slopes of ABLATED decode graphs (fusion-faithful, RTT-
free — see perf_common.py for why single-call timing lies on this tunnel).

Each variant runs K1 and K2 steps of a scan inside one jit; the slope
(t2-t1)/(K2-K1) is that graph's true per-step device time. full - variant
attributes the removed component's in-context cost.

Variants: full | no_attn | no_gather (attend only to the current token) |
no_head (skip lm_head matmul, sample from hidden slice) | no_write (skip
the deferred KV scatter) | no_mlp

Run: python scripts/perf_slope.py [batch] [width_pages] [variant ...]
"""

from __future__ import annotations

import math
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
sys.path.insert(0, "scripts")

from perf_common import measure_rtt

from dynamo_tpu.engine.sampler import sample
from dynamo_tpu.models import get_config, init_params, make_kv_cache
from dynamo_tpu.models.transformer import rms_norm, rope, write_kv_stack

MODEL = "qwen3-0.6b"
BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 8
WIDTH = int(sys.argv[2]) if len(sys.argv) > 2 else 32
VARIANTS = sys.argv[3:] or ["full", "no_attn", "no_gather", "no_head",
                            "no_write", "no_mlp"]
PAGE_SIZE = 16
NUM_PAGES = max(1024, BATCH * WIDTH + 8)
K1, K2 = 8, 40

cfg = get_config(MODEL)
params = jax.jit(lambda k: init_params(k, cfg))(jax.random.PRNGKey(0))


def decode_step(params, kv, tokens, positions, tables, kv_lens, variant):
    """Trimmed copy of forward_decode with ablation switches (probe-only:
    keeping ablation flags out of the product path)."""
    x = params["embed"][tokens][:, None, :]
    pos2 = positions[:, None]
    ks, vs = [], []
    for lp in params["layers"]:
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = jnp.einsum("bth,hqd->btqd", h, lp["wq"])
        k = jnp.einsum("bth,hkd->btkd", h, lp["wk"])
        v = jnp.einsum("bth,hkd->btkd", h, lp["wv"])
        if cfg.qk_norm:
            q = rms_norm(q, lp["q_norm"], cfg.rms_eps)
            k = rms_norm(k, lp["k_norm"], cfg.rms_eps)
        q = rope(q, pos2, cfg.rope_theta)
        k = rope(k, pos2, cfg.rope_theta)
        ks.append(k)
        vs.append(v)
        if variant == "no_attn":
            attn = q  # keep shapes; drop all attention math
        elif variant == "no_gather":
            # attention math against ONLY the current token (no KV reads)
            qg = q.reshape(BATCH, cfg.n_kv_heads, -1, cfg.head_dim)
            cur = jnp.einsum("bkgh,bkh->bkg", qg.astype(jnp.float32),
                             k[:, 0].astype(jnp.float32))
            probs = jax.nn.softmax(cur[..., None], axis=-1)
            attn = (probs[..., 0][..., None]
                    * v[:, 0].astype(jnp.float32)[:, :, None, :]) \
                .reshape(BATCH, 1, cfg.n_q_heads, cfg.head_dim) \
                .astype(q.dtype)
        elif variant.startswith("pool"):
            from dynamo_tpu.ops.paged_attention import (
                paged_attention_decode_pool,
            )

            ppc = int(variant[4:]) if len(variant) > 4 else 8
            attn = paged_attention_decode_pool(
                q, kv, len(ks) - 1, tables, kv_lens, k, v,
                pages_per_chunk=ppc)
        else:
            layer_idx = len(ks) - 1
            from dynamo_tpu.models.transformer import (
                paged_attention_decode_xla,
            )

            attn = paged_attention_decode_xla(
                q, kv, layer_idx, tables, kv_lens, k, v)
        x = x + jnp.einsum("btqd,qdh->bth", attn, lp["wo"])
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        if variant != "no_mlp":
            g = jnp.einsum("bth,hm->btm", h, lp["w_gate"])
            u = jnp.einsum("bth,hm->btm", h, lp["w_up"])
            x = x + jnp.einsum("btm,mh->bth", jax.nn.silu(g) * u,
                               lp["w_down"])
    if variant != "no_write":
        kv = write_kv_stack(kv, jnp.stack(ks), jnp.stack(vs), tables, pos2,
                            jnp.ones((BATCH, 1), bool))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    if variant == "no_head":
        logits = jnp.pad(x[:, 0, :].astype(jnp.float32),
                         ((0, 0), (0, cfg.vocab_size - cfg.hidden)))
    else:
        logits = jnp.einsum("bth,hv->btv", x,
                            params["embed"].T).astype(jnp.float32)[:, 0]
    return kv, logits


def build(variant, k_steps):
    def multi(params, kv, tokens, positions, tables, kv_lens, temp, top_p,
              top_k, seeds, steps):
        def body(carry, _):
            kv, toks, pos, lens, sidx = carry
            kv, logits = decode_step(params, kv, toks, pos, tables, lens,
                                     variant)
            nxt = sample(logits, temp, top_p, top_k, seeds, sidx)
            return (kv, nxt, pos + 1, lens + 1, sidx + 1), nxt

        (kv, *_), toks = jax.lax.scan(
            body, (kv, tokens, positions, kv_lens, steps), None,
            length=k_steps)
        return kv, toks

    return jax.jit(multi, donate_argnums=(1,))


def main():
    tables = np.zeros((BATCH, WIDTH), np.int32)
    nxt = 1
    for b in range(BATCH):
        tables[b] = np.arange(nxt, nxt + WIDTH)
        nxt += WIDTH
    tables_j = jnp.asarray(tables)
    kv_lens = jnp.full((BATCH,), WIDTH * PAGE_SIZE - K2 - 4, jnp.int32)
    tokens = jnp.zeros((BATCH,), jnp.int32)
    positions = kv_lens - 1
    temp = jnp.zeros((BATCH,), jnp.float32)
    top_p = jnp.ones((BATCH,), jnp.float32)
    top_k = jnp.zeros((BATCH,), jnp.int32)
    seeds = jnp.zeros((BATCH,), jnp.uint32)
    steps = jnp.zeros((BATCH,), jnp.int32)

    rtt = measure_rtt()
    print(f"RTT {rtt:.1f} ms", flush=True)

    for variant in VARIANTS:
        try:
            slopes = {}
            for k in (K1, K2):
                fn = build(variant, k)
                kv = jax.jit(
                    lambda: make_kv_cache(cfg, NUM_PAGES, PAGE_SIZE))()

                def call(kv):
                    kv, toks = fn(params, kv, tokens, positions, tables_j,
                                  kv_lens, temp, top_p, top_k, seeds, steps)
                    np.asarray(toks)
                    return kv

                kv = call(kv)  # compile + warm
                n = 5
                t0 = time.perf_counter()
                for _ in range(n):
                    kv = call(kv)
                slopes[k] = (time.perf_counter() - t0) / n * 1e3
            per_step = (slopes[K2] - slopes[K1]) / (K2 - K1)
            print(f"{variant:10s} k{K1}={slopes[K1]:7.1f} ms "
                  f"k{K2}={slopes[K2]:7.1f} ms -> {per_step:6.3f} ms/step",
                  flush=True)
        except Exception as exc:  # noqa: BLE001
            print(f"{variant:10s} FAILED {exc!r}", flush=True)


if __name__ == "__main__":
    main()
