#!/usr/bin/env python
"""Federation chaos gate: 3 cells, an open-loop ramp to ~1M sessions,
one cell killed mid-ramp and one evacuated gracefully, asserting zero
client errors on the evacuation path, errors pinned to the loss
window, bounded RSS, residency-hit-rate recovery inside its budget,
SLO goodput held after failover, residency routing beating the
pressure-only baseline on cached-turn TTFT, and zero ProtocolMonitor
violations (dynamo_tpu/mocker/federation_chaos.py;
docs/federation.md). Exit code gates the chaos-federation CI job; the
JSON report uploads as an artifact.

    python scripts/chaos_federation.py --out chaos-federation
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if __name__ == "__main__":
    os.environ.setdefault("DYNT_LOG_LEVEL", "WARNING")
    from dynamo_tpu.mocker.federation_chaos import main

    sys.exit(main())
