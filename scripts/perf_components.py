"""Component-level decode timing on the attached chip (see perf_probe.py
for the RTT discipline). Each probe compiles + runs in sequence and prints
immediately; a tunnel failure kills at most the current probe.

Run: python scripts/perf_components.py [batch] [width_pages] [probe ...]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from dynamo_tpu.engine.sampler import sample
from dynamo_tpu.models import get_config, init_params, make_kv_cache
from dynamo_tpu.models.transformer import (
    forward_decode,
    paged_attention_decode_xla,
    rms_norm,
    write_kv_stack,
)

MODEL = "qwen3-0.6b"
BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 8
WIDTH = int(sys.argv[2]) if len(sys.argv) > 2 else 32
ONLY = set(sys.argv[3:])
# Fewer layers -> small HLO -> the flaky remote compiler returns quickly;
# per-layer costs scale linearly so report both raw and x28 numbers.
PROBE_LAYERS = int(__import__("os").environ.get("PROBE_LAYERS", "4"))
PAGE_SIZE = 16
NUM_PAGES = max(1024, BATCH * WIDTH + 8)

cfg = get_config(MODEL)
params = jax.jit(lambda k: init_params(k, cfg))(jax.random.PRNGKey(0))
kv = jax.jit(lambda: make_kv_cache(cfg, NUM_PAGES, PAGE_SIZE))()

tables = np.zeros((BATCH, WIDTH), np.int32)
nxt = 1
for b in range(BATCH):
    tables[b] = np.arange(nxt, nxt + WIDTH)
    nxt += WIDTH
tables_j = jnp.asarray(tables)
kv_lens = jnp.full((BATCH,), WIDTH * PAGE_SIZE - 8, jnp.int32)
tokens = jnp.zeros((BATCH,), jnp.int32)
positions = kv_lens - 1
active = jnp.ones((BATCH,), bool)
temp = jnp.zeros((BATCH,), jnp.float32)
top_p = jnp.ones((BATCH,), jnp.float32)
top_k = jnp.zeros((BATCH,), jnp.int32)
seeds = jnp.zeros((BATCH,), jnp.uint32)
steps = jnp.zeros((BATCH,), jnp.int32)


sys.path.insert(0, "scripts")
import perf_common

RTT = perf_common.measure_rtt()
print(f"RTT {RTT:.1f} ms", flush=True)


def timeit(name, fn, *args, n=10):
    if ONLY and name not in ONLY:
        return
    try:
        dt = perf_common.timeit(fn, *args, n=n)
        print(f"{name:16s} {dt:8.3f} ms", flush=True)
    except Exception as exc:  # noqa: BLE001
        print(f"{name:16s} FAILED {exc!r}", flush=True)


q = jnp.zeros((BATCH, 1, cfg.n_q_heads, cfg.head_dim), jnp.bfloat16)
kc = jnp.zeros((BATCH, 1, cfg.n_kv_heads, cfg.head_dim), jnp.bfloat16)


@jax.jit
def fwd_only(kv, tokens):
    _, logits = forward_decode(params, cfg, tokens, positions, kv,
                               tables_j, kv_lens, active)
    return logits.sum()


@jax.jit
def attn_all(kv, q):
    acc = jnp.zeros((), jnp.float32)
    for layer in range(PROBE_LAYERS):
        o = paged_attention_decode_xla(q, kv, layer, tables_j, kv_lens,
                                       kc, kc)
        acc += o.astype(jnp.float32).sum()
    return acc


@jax.jit
def gather_all(kv):
    acc = jnp.zeros((), jnp.float32)
    for layer in range(PROBE_LAYERS):
        acc += kv[layer, 0][tables_j].astype(jnp.float32).sum()
        acc += kv[layer, 1][tables_j].astype(jnp.float32).sum()
    return acc


@jax.jit
def stream_all(kv):
    return kv.astype(jnp.float32).sum()


x1 = jnp.zeros((BATCH, 1, cfg.hidden), jnp.bfloat16)


@jax.jit
def lmhead(x):
    h = rms_norm(x, params["final_norm"], cfg.rms_eps)
    return jnp.einsum("bth,hv->btv", h,
                      params["embed"].T).astype(jnp.float32).sum()


logits0 = jnp.zeros((BATCH, cfg.vocab_size), jnp.float32)


@jax.jit
def samp(logits):
    return sample(logits, temp, top_p, top_k, seeds, steps).sum()


@jax.jit
def mlp_stack(x):
    # all layers' matmuls minus attention: the pure weight-stream cost
    acc = jnp.zeros((), jnp.float32)
    h = x
    for lp in params["layers"][:PROBE_LAYERS]:
        a = rms_norm(h, lp["attn_norm"], cfg.rms_eps)
        qh = jnp.einsum("bth,hqd->btqd", a, lp["wq"])
        kh2 = jnp.einsum("bth,hkd->btkd", a, lp["wk"])
        vh = jnp.einsum("bth,hkd->btkd", a, lp["wv"])
        o = jnp.einsum("btqd,qdh->bth", qh, lp["wo"])
        m = rms_norm(h + o, lp["mlp_norm"], cfg.rms_eps)
        g = jnp.einsum("bth,hm->btm", m, lp["w_gate"])
        u = jnp.einsum("bth,hm->btm", m, lp["w_up"])
        d = jnp.einsum("btm,mh->bth", jax.nn.silu(g) * u, lp["w_down"])
        h = h + d
        acc += kh2.astype(jnp.float32).sum() + vh.astype(jnp.float32).sum()
    return acc + h.astype(jnp.float32).sum()


timeit("fwd_1step", fwd_only, kv, tokens)
timeit("attn_%dL" % PROBE_LAYERS, attn_all, kv, q)
timeit("gather_%dL" % PROBE_LAYERS, gather_all, kv)
timeit("stream_pool", stream_all, kv)
timeit("mlp_stack", mlp_stack, x1)
timeit("lmhead", lmhead, x1)
timeit("sampler", samp, logits0)

state = {"kv": kv}
ks = jnp.zeros((cfg.n_layers, BATCH, 1, cfg.n_kv_heads, cfg.head_dim),
               jnp.bfloat16)
scat = jax.jit(
    lambda kv: write_kv_stack(kv, ks, ks, tables_j, positions[:, None],
                              active[:, None]),
    donate_argnums=(0,))
if not ONLY or "scatter" in ONLY:
    try:
        def scat_call():
            out = scat(state["kv"])
            state["kv"] = out
            np.asarray(out[0, 0, 0, 0, 0, 0])

        scat_call()
        t0 = time.perf_counter()
        for _ in range(10):
            scat_call()
        dt = max((time.perf_counter() - t0) / 10 * 1e3 - perf_common.RTT_MS, 0.0)
        print(f"{'scatter':16s} {dt:8.3f} ms", flush=True)
    except Exception as exc:  # noqa: BLE001
        print(f"scatter FAILED {exc!r}", flush=True)

wbytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
print(f"params {wbytes/1e9:.3f} GB -> {wbytes/819e9*1e3:.2f} ms weight "
      f"stream floor; pool {kv.size*2/1e9:.2f} GB", flush=True)
