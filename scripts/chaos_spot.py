"""Chaos-spot CI driver: continuously evict+replace mocker workers
under a rising open-loop ramp and assert the fast-start plane made the
churn invisible — zero client-visible errors, streams bit-identical to
an uneviced run, SLO goodput held, every replacement's first token
inside the pinned cold-start budget, and capacity tracking the
planner's wish after every cycle (docs/elasticity.md arrival ladder).

Headless, CPU-only, chip-free: everything runs in-process through
dynamo_tpu.mocker.spot_chaos. Exits nonzero when any assertion fails,
so the chaos-spot job gates on the seconds-scale arrival contract.

    python scripts/chaos_spot.py --out chaos-spot
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser("chaos_spot")
    parser.add_argument("--out", default="chaos-spot",
                        help="report output directory")
    parser.add_argument("--quick", action="store_true",
                        help="smaller ramp / one cycle (local smoke)")
    parser.add_argument("--cycles", type=int, default=None,
                        help="override evict+replace cycle count")
    args = parser.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("DYNT_LOG_LEVEL", "WARNING")

    from dynamo_tpu.mocker.spot_chaos import SpotChaosParams, run_scenario

    params = SpotChaosParams()
    if args.quick:
        params = SpotChaosParams(n_workers=2, n_streams=10,
                                 evict_cycles=1, streams_before_evict=3)
    if args.cycles is not None:
        params.evict_cycles = args.cycles
    report = asyncio.run(run_scenario(params))

    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "chaos_spot_report.json")
    with open(path, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)

    print(f"report: {path}")
    for chk in report["assertions"]:
        mark = "PASS" if chk["ok"] else "FAIL"
        print(f"  [{mark}] {chk['name']}")
        if not chk["ok"]:
            print(f"         {json.dumps(chk['detail'])[:400]}")
    for n, cyc in enumerate(report["spot"]["cycles"]):
        cold = cyc["coldstart"] or {}
        print(f"cycle {n}: first token in "
              f"{(cold.get('total_secs') or 0):.2f}s "
              f"(budget {params.coldstart_budget_secs:.2f}s), capacity "
              f"recovered in {(cyc['recovered_secs'] or -1):.2f}s")
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
