"""Generate the shipped pre-swept planner profiles (VERDICT r4 item 10;
ref: components/src/dynamo/planner/utils/pre_swept_results/ — the
reference checks in per-GPU NPZ interpolation data so the planner boots
with zero profiling).

Method: the rapid analytic sweep (profiler/timing_model.py) generates
the grid SHAPE; real-chip anchors measured this round (BASELINE.md r5)
calibrate its absolute level — the grid is scaled by
measured/predicted at the anchor operating point. This keeps the curves
physically shaped (roofline over batch/context) while pinning them to
what the chip actually did, without hours of tunnel-polluted serving
sweeps (tunnel TTFT/ITL are RTT artifacts — BASELINE.md caveat).

Usage: python scripts/gen_pre_swept.py   (writes into
dynamo_tpu/planner/pre_swept/<chip>/<model>/)
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamo_tpu.models import get_config  # noqa: E402
from dynamo_tpu.planner.interpolation import (  # noqa: E402
    save_decode_profile,
    save_prefill_profile,
)
from dynamo_tpu.profiler.chips import get_chip  # noqa: E402
from dynamo_tpu.profiler.timing_model import (  # noqa: E402
    TimingModel,
    rapid_decode_sweep,
    rapid_prefill_sweep,
)

ISLS = [128, 256, 512, 1024, 2048, 4096, 8192]
KV_USAGES = [0.1, 0.2, 0.35, 0.5, 0.65, 0.8, 0.95]
CONTEXTS = [256, 1024, 4096, 16384]

# Real-chip anchors, v5e single chip (BASELINE.md r5 measured):
#   decode: (batch, context, measured tok/s/chip) from bench.py
#   prefill: (chunk_len, measured tok/s/chip) from bench.py's prefill
#            block (pipelined chunks)
ANCHORS = {
    "qwen3-0.6b": {"decode": (8, 256, 2350.2), "prefill": (1024, 6098.4)},
    "mistral-7b": {"decode": (8, 256, 247.2), "prefill": (1024, 7425.0)},
}


def gen(chip: str, model_name: str, out_root: str) -> None:
    cfg = get_config(model_name)
    tm = TimingModel(cfg, get_chip(chip), num_chips=1)
    anchors = ANCHORS[model_name]

    b, ctx, measured = anchors["decode"]
    predicted = tm.decode_thpt_per_chip(float(b), float(ctx))
    dscale = measured / predicted
    decode = rapid_decode_sweep(tm, KV_USAGES, CONTEXTS)
    decode["z_thpt_per_chip"] = decode["z_thpt_per_chip"] * dscale
    decode["z_itl"] = decode["z_itl"] / dscale

    chunk, pmeasured = anchors["prefill"]
    ppred = tm.prefill_thpt_per_chip(float(chunk))
    pscale = pmeasured / ppred
    prefill = rapid_prefill_sweep(tm, ISLS)
    prefill["prefill_thpt_per_chip"] = (
        prefill["prefill_thpt_per_chip"] * pscale)
    prefill["prefill_ttft"] = prefill["prefill_ttft"] / pscale

    out = os.path.join(out_root, chip, model_name)
    save_prefill_profile(out, prefill["prefill_isl"],
                         prefill["prefill_ttft"],
                         prefill["prefill_thpt_per_chip"])
    save_decode_profile(out, decode["x_kv_usage"],
                        decode["y_context_length"], decode["z_itl"],
                        decode["z_thpt_per_chip"],
                        int(decode["max_kv_tokens"][0]))
    with open(os.path.join(out, "PROVENANCE.json"), "w") as f:
        json.dump({
            "method": "rapid TimingModel sweep calibrated to real-chip "
                      "anchors (scripts/gen_pre_swept.py)",
            "chip": chip, "model": model_name,
            "anchors": anchors,
            "decode_scale": round(float(dscale), 4),
            "prefill_scale": round(float(pscale), 4),
            "measured": "BASELINE.md r5 (2026-07-31, v5e via tunnel)",
        }, f, indent=1)
    print(f"{chip}/{model_name}: decode_scale={dscale:.3f} "
          f"prefill_scale={pscale:.3f} -> {out}")


def main() -> None:
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "dynamo_tpu", "planner", "pre_swept")
    for model in ANCHORS:
        gen("v5e", model, root)


if __name__ == "__main__":
    main()
