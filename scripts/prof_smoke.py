#!/usr/bin/env python
"""Prof smoke: device-time attribution plane end-to-end, chip-free.

CI entrypoint (the `prof-smoke` job): bring up a mocker worker and the
OpenAI frontend on in-process planes with sizeable modeled step times,
run a short burst of chat requests, then assert

  * the per-request decomposition invariant — every ok timeline's
    queue + host + device components sum to within tolerance of its
    measured TTFT (the attributable TTFT that retires the tunnel-RTT
    hypothesis),
  * `dynamo_ttft_device_ms` exported with a `trace_id` exemplar on the
    OpenMetrics scrape,
  * `/debug/profile` runs an on-demand jax.profiler capture and
    returns a trace artifact directory with files in it,

and write the capture manifest + recorder snapshot as CI artifacts.
Exits nonzero on any violated invariant.

Usage: python scripts/prof_smoke.py [--requests N] [--out DIR]
"""

from __future__ import annotations

import argparse
import asyncio
import http.server
import json
import os
import pathlib
import sys
import threading
import uuid

# Runnable as `python scripts/prof_smoke.py` from the repo root.
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))

PASS_TIMEOUT = 120.0
# Sum tolerance: modeled step times are ~100ms so CI sleep jitter sits
# well inside 10%; keep a small absolute floor for the queue edge.
SUM_TOLERANCE_FRAC = 0.10
SUM_TOLERANCE_ABS_MS = 5.0


def start_collector():
    class Collector(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            self.rfile.read(length)
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *args):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Collector)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


async def run_pass(n_requests: int):
    import aiohttp

    from dynamo_tpu.frontend import Frontend
    from dynamo_tpu.mocker import MockerConfig, MockerWorker
    from dynamo_tpu.runtime import DistributedRuntime, RuntimeConfig

    cfg = RuntimeConfig.from_env()
    cfg.discovery_backend = "mem"
    cfg.discovery_path = uuid.uuid4().hex
    cfg.request_plane = "mem"
    cfg.event_plane = "mem"
    cfg.system_enabled = False

    rt = await DistributedRuntime(cfg).start()
    worker = MockerWorker(
        rt, model_name="mock-model",
        config=MockerConfig(prefill_us_per_token=400.0,
                            decode_base_ms=15.0,
                            max_prefill_tokens_per_step=128,
                            num_blocks=512),
        load_publish_interval=0.2)
    await worker.start()
    frontend = Frontend(rt, host="127.0.0.1", port=0,
                        router_mode="round_robin")
    await frontend.start()
    for _ in range(100):
        if frontend.manager.get("mock-model") is not None:
            break
        await asyncio.sleep(0.05)
    else:
        raise RuntimeError("mocker never registered with the frontend")

    base = f"http://127.0.0.1:{frontend.port}"

    async def one_request(session, i):
        payload = {
            "model": "mock-model",
            "messages": [{"role": "user",
                          "content": f"prof smoke {i} " + "x" * 200}],
            "max_tokens": 4,
        }
        async with session.post(f"{base}/v1/chat/completions",
                                json=payload) as resp:
            body = await resp.json()
            assert resp.status == 200, body
            return body

    async with aiohttp.ClientSession() as session:
        await asyncio.gather(*[one_request(session, i)
                               for i in range(n_requests)])
        # On-demand capture WHILE the serving process is alive.
        async with session.get(
                f"{base}/debug/profile?duration_ms=200") as resp:
            profile = await resp.json()
            profile["_status"] = resp.status
        async with session.get(f"{base}/debug/requests") as resp:
            snapshot = await resp.json()
        async with session.get(
                f"{base}/metrics",
                headers={"Accept":
                         "application/openmetrics-text"}) as resp:
            metrics_text = await resp.text()

    await frontend.close()
    await worker.close()
    await rt.shutdown()
    return profile, snapshot, metrics_text


def check_decomposition(snapshot) -> tuple[list[dict], list[str]]:
    """The invariant the plane exists for: every ok timeline's
    queue + host + device sums to its measured TTFT within tolerance."""
    rows, failures = [], []
    done = [tl for tl in snapshot.get("completed", [])
            if tl.get("status") == "ok"
            and tl.get("phases", {}).get("first_token")]
    if not done:
        return rows, ["no ok timelines with a first_token phase"]
    for tl in done:
        phases, device = tl["phases"], tl.get("device", {})
        ttft_ms = (phases["first_token"] - phases["received"]) * 1e3
        queue_ms = (phases.get("scheduled", phases["received"])
                    - phases["received"]) * 1e3
        host_ms = device.get("prefill_host_ms", 0.0)
        device_ms = device.get("prefill_device_ms", 0.0)
        total = queue_ms + host_ms + device_ms
        row = {"request_id": tl["request_id"],
               "ttft_ms": round(ttft_ms, 3),
               "queue_ms": round(queue_ms, 3),
               "host_ms": round(host_ms, 3),
               "device_ms": round(device_ms, 3),
               "sum_ms": round(total, 3)}
        rows.append(row)
        if device_ms <= 0:
            failures.append(f"{tl['request_id']}: no device time "
                            "attributed")
        tol = SUM_TOLERANCE_FRAC * ttft_ms + SUM_TOLERANCE_ABS_MS
        if abs(total - ttft_ms) > tol:
            failures.append(
                f"{tl['request_id']}: decomposition sum {total:.1f}ms "
                f"vs TTFT {ttft_ms:.1f}ms exceeds tolerance {tol:.1f}ms")
    return rows, failures


def main() -> int:
    parser = argparse.ArgumentParser("prof_smoke")
    parser.add_argument("--requests", type=int, default=8)
    parser.add_argument("--out", default=".",
                        help="artifact directory (prof-smoke-manifest."
                             "json + prof-smoke-recorder.json)")
    args = parser.parse_args()

    srv, endpoint = start_collector()
    # Before the first get_tracer()/get_recorder(): exemplars need a
    # live trace context, the debug endpoints need the opt-in.
    os.environ["DYNT_OTLP_ENDPOINT"] = endpoint
    os.environ["DYNT_DEBUG_ENDPOINTS"] = "1"
    os.environ.setdefault("DYNT_PROF_DIR",
                          str(pathlib.Path(args.out) / "captures"))

    profile, snapshot, metrics_text = asyncio.run(
        asyncio.wait_for(run_pass(args.requests), PASS_TIMEOUT))
    srv.shutdown()

    rows, failures = check_decomposition(snapshot)

    if profile.get("_status") != 200:
        failures.append(f"/debug/profile answered {profile}")
    elif not profile.get("files"):
        failures.append(f"profile capture wrote no files: {profile}")

    ttft_lines = [line for line in metrics_text.splitlines()
                  if line.startswith("dynamo_ttft_device_ms")]
    if not ttft_lines:
        failures.append("dynamo_ttft_device_ms missing from /metrics")
    elif not any("# {" in line and "trace_id=" in line
                 for line in ttft_lines):
        failures.append("dynamo_ttft_device_ms carries no trace_id "
                        "exemplar")

    out = pathlib.Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    (out / "prof-smoke-manifest.json").write_text(json.dumps({
        "profile": profile,
        "decomposition": rows,
        "failures": failures,
    }, indent=2))
    (out / "prof-smoke-recorder.json").write_text(
        json.dumps(snapshot, indent=2))

    print(f"prof-smoke: {len(rows)} decomposed timelines, capture at "
          f"{profile.get('trace_dir')!r} "
          f"({len(profile.get('files') or [])} files)")
    for failure in failures:
        print(f"FAIL: {failure}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
