"""Shared timing discipline for probes on the tunnel-attached chip.

`jax.block_until_ready` does NOT synchronize over the tunnel (returns
immediately) and every host readback costs ~50-300ms RTT, so: force a small
readback per call, measure the RTT with a trivial kernel, subtract it.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

RTT_MS = 0.0


def measure_rtt(n: int = 20) -> float:
    """Round-trip of a trivial dispatch+readback; sets the module RTT."""
    global RTT_MS

    @jax.jit
    def tiny(x):
        return x + 1

    x = jnp.zeros((), jnp.float32)
    float(tiny(x))
    t0 = time.perf_counter()
    for _ in range(n):
        float(tiny(x))
    RTT_MS = (time.perf_counter() - t0) / n * 1e3
    return RTT_MS


def timeit(fn, *args, n: int = 10) -> float:
    """Mean ms/call of `fn` (must return a scalar/tiny array), RTT
    subtracted. Compiles on the first (untimed) call."""
    np.asarray(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        np.asarray(fn(*args))
    return max((time.perf_counter() - t0) / n * 1e3 - RTT_MS, 0.0)
