"""Measure a unified decode step built on the PUBLIC jax Pallas paged
attention kernel with a head-major pool layout [L, 2, kh, P, ps, hd]:
per-layer current-KV writes into the pool, then chunked-DMA kernel reads.
Slope-paired like perf_slope.py. Decides whether the product pool layout
refactor pays.

Run: python scripts/perf_public_kernel.py [batch] [width] [pages_per_block]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
sys.path.insert(0, "scripts")

from jax.experimental.pallas.ops.tpu.paged_attention.paged_attention_kernel import (  # noqa: E501
    paged_attention,
)
from perf_common import measure_rtt

from dynamo_tpu.engine.sampler import sample
from dynamo_tpu.models import get_config, init_params
from dynamo_tpu.models.transformer import rms_norm, rope

MODEL = "qwen3-0.6b"
BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 8
WIDTH = int(sys.argv[2]) if len(sys.argv) > 2 else 32
PPB = int(sys.argv[3]) if len(sys.argv) > 3 else 8  # pages per compute block
MODE = sys.argv[4] if len(sys.argv) > 4 else "full"  # full|nowrite|noattn
PAGE_SIZE = 16
NUM_PAGES = max(1024, BATCH * WIDTH + 8)
K1, K2 = 8, 40

cfg = get_config(MODEL)
params = jax.jit(lambda k: init_params(k, cfg))(jax.random.PRNGKey(0))


def decode_step(params, kv, tokens, positions, tables, kv_lens):
    """Unified decode: write current K/V into the head-major pool per
    layer, then public chunked-DMA paged attention over the full length."""
    b = tokens.shape[0]
    pos2 = positions[:, None]
    x = params["embed"][tokens][:, None, :]
    page_of = positions // PAGE_SIZE
    page_idx = jnp.take_along_axis(tables, page_of[:, None], axis=1)[:, 0]
    slot = positions % PAGE_SIZE
    for layer_idx, lp in enumerate(params["layers"]):
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q = jnp.einsum("bth,hqd->btqd", h, lp["wq"])
        k = jnp.einsum("bth,hkd->btkd", h, lp["wk"])
        v = jnp.einsum("bth,hkd->btkd", h, lp["wv"])
        if cfg.qk_norm:
            q = rms_norm(q, lp["q_norm"], cfg.rms_eps)
            k = rms_norm(k, lp["k_norm"], cfg.rms_eps)
        q = rope(q, pos2, cfg.rope_theta)
        k = rope(k, pos2, cfg.rope_theta)
        # kv: [L, 2, kh, P, ps, hd]; write row (kh, page_idx[b], slot[b])
        kc = k[:, 0].transpose(1, 0, 2)  # [kh, B, hd]
        vc = v[:, 0].transpose(1, 0, 2)
        if MODE != "nowrite":
            kv = kv.at[layer_idx, 0, :, page_idx, slot].set(
                kc.transpose(1, 0, 2).astype(kv.dtype))
            kv = kv.at[layer_idx, 1, :, page_idx, slot].set(
                vc.transpose(1, 0, 2).astype(kv.dtype))
        if MODE == "noattn":
            attn = q[:, 0]
        else:
            attn = paged_attention(
                q[:, 0], kv[layer_idx, 0], kv[layer_idx, 1], kv_lens,
                tables, pages_per_compute_block=PPB,
            )  # [B, qh, hd]
        x = x + jnp.einsum("btqd,qdh->bth", attn[:, None], lp["wo"])
        hm = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        g = jnp.einsum("bth,hm->btm", hm, lp["w_gate"])
        u = jnp.einsum("bth,hm->btm", hm, lp["w_up"])
        x = x + jnp.einsum("btm,mh->bth", jax.nn.silu(g) * u, lp["w_down"])
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    logits = jnp.einsum("bth,hv->btv", x,
                        params["embed"].T).astype(jnp.float32)[:, 0]
    return kv, logits


def build(k_steps):
    def multi(params, kv, tokens, positions, tables, kv_lens, temp, top_p,
              top_k, seeds, steps):
        def body(carry, _):
            kv, toks, pos, lens, sidx = carry
            kv, logits = decode_step(params, kv, toks, pos, tables, lens)
            nxt = sample(logits, temp, top_p, top_k, seeds, sidx)
            return (kv, nxt, pos + 1, lens + 1, sidx + 1), nxt

        (kv, *_), toks = jax.lax.scan(
            body, (kv, tokens, positions, kv_lens, steps), None,
            length=k_steps)
        return kv, toks

    return jax.jit(multi, donate_argnums=(1,))


def main():
    tables = np.zeros((BATCH, WIDTH), np.int32)
    nxt = 1
    for b in range(BATCH):
        tables[b] = np.arange(nxt, nxt + WIDTH)
        nxt += WIDTH
    tables_j = jnp.asarray(tables)
    kv_lens = jnp.full((BATCH,), WIDTH * PAGE_SIZE - K2 - 4, jnp.int32)
    tokens = jnp.zeros((BATCH,), jnp.int32)
    positions = kv_lens - 1
    temp = jnp.zeros((BATCH,), jnp.float32)
    top_p = jnp.ones((BATCH,), jnp.float32)
    top_k = jnp.zeros((BATCH,), jnp.int32)
    seeds = jnp.zeros((BATCH,), jnp.uint32)
    steps = jnp.zeros((BATCH,), jnp.int32)

    rtt = measure_rtt()
    print(f"RTT {rtt:.1f} ms (ppb={PPB})", flush=True)
    slopes = {}
    for k in (K1, K2):
        fn = build(k)
        kv = jax.jit(lambda: jnp.zeros(
            (cfg.n_layers, 2, cfg.n_kv_heads, NUM_PAGES, PAGE_SIZE,
             cfg.head_dim), jnp.bfloat16))()

        def call(kv):
            kv, toks = fn(params, kv, tokens, positions, tables_j,
                          kv_lens, temp, top_p, top_k, seeds, steps)
            np.asarray(toks)
            return kv

        kv = call(kv)
        n = 5
        t0 = time.perf_counter()
        for _ in range(n):
            kv = call(kv)
        slopes[k] = (time.perf_counter() - t0) / n * 1e3
        print(f"k{k}: {slopes[k]:.1f} ms", flush=True)
    per_step = (slopes[K2] - slopes[K1]) / (K2 - K1)
    print(f"public-kernel {MODE}: {per_step:.3f} ms/step",
          flush=True)


if __name__ == "__main__":
    main()
