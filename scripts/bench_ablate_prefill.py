"""Prefill-side floor ablation (VERDICT r4 item 2): locate where the
non-MXU time in a prefill chunk goes, mirroring bench_ablate2.py's
monkeypatch-then-time method on the pipelined prefill_chunk path
bench.py's prefill block uses (the only dispatch pattern the tunnel
measures faithfully — scan/pipelined benches only).

  full        unmodified prefill_chunk
  noattn      attention replaced by identity over V-shaped zeros (the
              projections + MLP remain: isolates SDPA cost)
  nowrite     write_kv_pages -> identity (no paged-pool writeback)
  nohead      final-token head matmul + sampler replaced by a dummy
  nonorm      rms_norm -> identity
  norope      rope -> identity

Usage: python -u scripts/bench_ablate_prefill.py <what> [model] [chunk]
(one config per process: monkeypatches must precede jit builds).
Prints one JSON line: {"ablation": ..., "tokens_per_sec": ..., "mfu"?}.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def apply_patch(what: str) -> None:
    import jax.numpy as jnp

    from dynamo_tpu.models import transformer

    if what == "noattn":
        def fake_attention(q, kv_cache, layer, block_tables, positions,
                          kv_lens):
            return jnp.zeros_like(q) + q * 1e-6  # keep deps, kill SDPA
        transformer.paged_attention_xla = fake_attention
        # the runner passes an attention_fn; main() below forces None +
        # DYNT_ATTENTION=xla so this module-level patch is the one used
    elif what == "nowrite":
        transformer.write_kv_pages = (
            lambda kv_cache, layer, k, v, *a, **kw: kv_cache)
    elif what == "nohead":
        orig = transformer.forward

        def patched(params, config, tokens, *a, **k):
            kv, logits = orig(params, config, tokens, *a, **k)
            fake = jnp.zeros((logits.shape[0], logits.shape[1], 1024),
                             jnp.float32) + tokens[:, :, None]
            return kv, fake
        transformer.forward = patched
        from dynamo_tpu.engine import model_runner

        model_runner.forward = patched
    elif what == "nonorm":
        transformer.rms_norm = lambda x, w, eps=1e-6: x
    elif what == "norope":
        transformer.rope = lambda x, positions, theta=10000.0: x
    elif what != "full":
        raise SystemExit(f"unknown ablation {what}")


def main() -> None:
    what = sys.argv[1]
    model = sys.argv[2] if len(sys.argv) > 2 else "mistral-7b"
    chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
    os.environ.setdefault("DYNT_ATTENTION",
                          "xla" if what == "noattn" else "auto")
    apply_patch(what)
    import numpy as np

    from dynamo_tpu.engine import ModelRunner, RunnerConfig
    from dynamo_tpu.models import get_config
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    config = get_config(model)
    kv_dtype = os.environ.get("DYNT_BENCH_KV_DTYPE", "int8"
                              if "7b" in model else "model")
    page_size = 16
    pages = chunk // page_size + 2
    runner = ModelRunner(
        config,
        RunnerConfig(page_size=page_size, num_pages=pages + 2,
                     max_batch=1, max_pages_per_seq=pages,
                     prefill_buckets=(256, chunk) if chunk > 256
                     else (256,),
                     kv_dtype=kv_dtype),
        make_mesh(MeshConfig()), seed=0)
    rng = np.random.default_rng(0)
    table = np.zeros(pages, np.int32)
    table[: chunk // page_size + 1] = np.arange(
        1, chunk // page_size + 2)
    prompt = rng.integers(0, config.vocab_size, chunk).astype(np.int32)
    n_chunks = 8

    def prefill_pass():
        pending = [runner.prefill_chunk(prompt, 0, table, chunk,
                                        (0.0, 1.0, 0, 0),
                                        return_device=True)
                   for _ in range(n_chunks)]
        for tok in pending:
            np.asarray(tok)

    prefill_pass()  # compile
    trials = []
    for _ in range(3):
        t0 = time.perf_counter()
        prefill_pass()
        trials.append(time.perf_counter() - t0)
    elapsed = sorted(trials)[1]
    tok_s = n_chunks * chunk / elapsed
    print(json.dumps({"ablation": what, "model": model, "chunk": chunk,
                      "tokens_per_sec": round(tok_s, 1),
                      "us_per_chunk": round(elapsed / n_chunks * 1e6, 1)}),
          flush=True)


if __name__ == "__main__":
    main()
