#!/usr/bin/env python
"""W4A16 kernel ablation CLI — variant x (bm, bn, gk) x geometry sweep
(dynamo_tpu/perf/q4_ablation.py) with a machine-readable JSON report.

The same command runs in two places:

  CI (`q4-parity` job): `python scripts/q4_ablate.py --interpret` —
    tiny geometry grid through the Pallas interpreter, every pack
    layout checked against q4_matmul_ref; exits nonzero on any parity
    failure, report uploaded as an artifact.

  Silicon (BENCH_r06): `python bench.py` attaches the flagship-geometry
    sweep as its `q4_ablation` block; running this script directly on a
    TPU host gives the same numbers standalone:
    `python scripts/q4_ablate.py --out q4-ablate`.

The report embeds the silicon acceptance bar (flagship decode
vs_baseline >= 0.5) so a captured BENCH_r06 is self-describing.

Usage: python scripts/q4_ablate.py [--interpret] [--m N]
         [--variants v1,v2] [--bm 256] [--bn 512,1024] [--gk 0,2,4]
         [--out DIR | --json PATH]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

# Runnable as `python scripts/q4_ablate.py` from the repo root.
sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))


def _ints(raw: str) -> list[int]:
    return [int(v) for v in raw.split(",") if v != ""]


def main() -> int:
    parser = argparse.ArgumentParser("q4_ablate")
    parser.add_argument("--interpret", action="store_true",
                        help="force the Pallas interpreter + tiny grid "
                             "(the CI parity mode)")
    parser.add_argument("--m", type=int, default=8,
                        help="activation rows (decode batch)")
    parser.add_argument("--variants", default="v1,v2")
    parser.add_argument("--bm", default="256", type=_ints)
    parser.add_argument("--bn", default="512,1024", type=_ints)
    parser.add_argument("--gk", default="0,2,4", type=_ints,
                        help="groups per k-step (0 = kernel auto)")
    parser.add_argument("--trials", type=int, default=3)
    parser.add_argument("--steps", type=int, default=16)
    parser.add_argument("--out", default=None,
                        help="artifact dir (writes q4-ablate-report.json)")
    parser.add_argument("--json", default=None,
                        help="explicit report path (wins over --out)")
    args = parser.parse_args()

    from dynamo_tpu.perf.q4_ablation import run_ablation

    report = run_ablation(
        mode="interpret" if args.interpret else "auto",
        m=args.m,
        variants=tuple(v for v in args.variants.split(",") if v),
        bms=tuple(args.bm), bns=tuple(args.bn), gks=tuple(args.gk),
        trials=args.trials, steps=args.steps,
    )

    path = None
    if args.json:
        path = pathlib.Path(args.json)
    elif args.out:
        path = pathlib.Path(args.out) / "q4-ablate-report.json"
    if path is not None:
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(report, indent=2))
        print(f"report: {path}")

    ran = [r for r in report["results"] if "skipped" not in r]
    print(f"mode={report['mode']} backend={report['backend']} "
          f"points={report['points']} ran={len(ran)} "
          f"parity_failures={len(report['parity_failures'])}")
    for geom, top in report.get("best", {}).items():
        print(f"  best[{geom}]: {top}")
    if report["parity_failures"]:
        for bad in report["parity_failures"]:
            print(f"PARITY FAIL: {bad}", file=sys.stderr)
        return 1
    if not ran:
        print("no points ran", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
