"""Chaos-drain CI driver: evict a mocker worker mid-decode and assert
the departure ladder made it invisible — zero client-visible errors,
streams bit-identical to an undrained run, zero re-prefill tokens on
the KV-handoff path, drain inside the deadline, drained worker gone
from router selection (docs/fault-tolerance.md departure ladder).

Headless, CPU-only, chip-free: everything runs in-process through
dynamo_tpu.mocker.drain_chaos. Exits nonzero when any assertion fails,
so the chaos-drain job gates on the zero-drop contract.

    python scripts/chaos_drain.py --out chaos-drain
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser("chaos_drain")
    parser.add_argument("--out", default="chaos-drain",
                        help="report output directory")
    parser.add_argument("--quick", action="store_true",
                        help="smaller fleet/streams (local smoke)")
    parser.add_argument("--no-fallback-pass", action="store_true",
                        help="skip the forced replay-fallback eviction")
    args = parser.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("DYNT_LOG_LEVEL", "WARNING")

    from dynamo_tpu.mocker.drain_chaos import DrainChaosParams, run_scenario

    params = DrainChaosParams()
    if args.quick:
        params = DrainChaosParams(n_workers=2, n_streams=6,
                                  max_tokens=32, decode_base_ms=20.0)
    report = asyncio.run(run_scenario(
        params, fallback_pass=not args.no_fallback_pass))

    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "chaos_drain_report.json")
    with open(path, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)

    print(f"report: {path}")
    for chk in report["assertions"]:
        mark = "PASS" if chk["ok"] else "FAIL"
        print(f"  [{mark}] {chk['name']}")
        if not chk["ok"]:
            print(f"         {json.dumps(chk['detail'])[:400]}")
    rep = report["drain_handoff"]["drain_report"] or {}
    print(f"drain: {len(rep.get('handoff') or [])} handoff, "
          f"{len(rep.get('replay') or [])} replay, "
          f"{rep.get('errored', '?')} errored in "
          f"{rep.get('duration_ms', 0):.0f}ms; "
          f"re-prefill={report['drain_handoff']['reprefill_tokens']} tokens")
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
