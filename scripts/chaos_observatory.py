#!/usr/bin/env python
"""Observatory chaos gate: a simulated 2-pool fleet watched by the real
observatory stack (collector breakers, histogram-merge rollups,
burn-rate alerting, capture bundles) under an injected clock, asserting
the fast burn-rate alert fires within the detection budget and names
the degraded pool, a complete capture bundle lands in the spool, the
dead target's scrape breaker bounds the damage and re-closes after
revival, the alert resolves after the heal, the clean arm produces
zero transitions/bundles, and zero ProtocolMonitor violations
(dynamo_tpu/mocker/observatory_chaos.py; docs/observability.md). Exit
code gates the obs-watch CI job; the JSON report + bundle spool upload
as artifacts.

    python scripts/chaos_observatory.py --out chaos-observatory
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if __name__ == "__main__":
    os.environ.setdefault("DYNT_LOG_LEVEL", "WARNING")
    from dynamo_tpu.mocker.observatory_chaos import main

    sys.exit(main())
