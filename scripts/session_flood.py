#!/usr/bin/env python
"""Session-flood gate: >=100k concurrent synthetic sessions against an
in-process router-replica pair, asserting bounded RSS (TinyLFU holds
the radix index and session store under their caps) and pin-set
convergence across replicas (dynamo_tpu/mocker/session_flood.py;
docs/prompt-caching.md). Exit code gates the session-flood CI job; the
JSON report uploads as an artifact.

    python scripts/session_flood.py --sessions 100000 --out session-flood
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if __name__ == "__main__":
    os.environ.setdefault("DYNT_LOG_LEVEL", "WARNING")
    from dynamo_tpu.mocker.session_flood import main

    sys.exit(main())
